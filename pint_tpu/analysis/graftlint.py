"""graftlint — project-specific invariant linter for pint_tpu.

Reference: the conventions in CLAUDE.md / ARCHITECTURE.md that nothing
enforced mechanically until this pass existed (the PhaseOffset
"silently inert" bug was caught by hand in the SINK_PAR sweep; rules
G1-G8 make that class of bug a lint failure instead of an archaeology
find). The GLS machinery these invariants protect is the numerically
delicate path of van Haasteren & Vallisneri (arXiv:1407.6710): one
silent retrace, host fallback, or dtype demotion corrupts results
without failing a test.

Rules (see ARCHITECTURE.md "Static analysis" for the table):

  G1  no Python-scalar coercion (float/int/bool/.item/.tolist) of
      traced values inside jit-reachable code — each forces a device
      sync or bakes a trace constant (a silent retrace per value)
  G2  no numpy calls on potentially-traced data in models/ compute
      paths — np.* on a tracer either errors late or silently hauls
      the computation to host
  G3  every registered Component subclass cites its reference
      file/symbol in the class docstring
  G4  every numeric parameter slot has a param_dimensions() spec
      (static: the class must define/inherit an override; dynamic:
      bare instances and the SINK_PAR kitchen-sink model must have
      full _spec_lookup coverage)
  G5  hybrid-Jacobian claims are paired (linear_design_names defined
      iff linear_design_local is) and every claiming component is
      exercised by test_all_components.py's SINK_PAR sweep
  G6  timeout bounds on everything that can touch a wedged backend:
      (a) tools// scripts/ — shell lines invoking python carry
      `timeout`, subprocess calls pass timeout=, in-process backend
      touches are preceded by a bounded probe
      (bench.accelerator_responsive); (b) the production dispatch
      layer (fitter/gls/wideband_fitter/config + serve/ + parallel/)
      — a jit-product (name assigned from jax.jit(...), a
      jit-decorated kernel, or an immediate jax.jit(...)(...) call)
      must not be CALLED directly: route it through
      pint_tpu.runtime.DispatchSupervisor.dispatch (pass the callable
      as an argument), which owns the watchdog deadline / breaker /
      host-failover policy. Sanctioned internal sites (closures the
      supervisor itself executes, the RTT probe) carry pragmas or
      allowlist entries.
  G7  jax.config.update only in sanctioned entry points (the config
      is process-global; a stray update mid-library flips x64 or the
      platform under every other caller)
  G8  no functools.lru_cache/cache on methods (the cache keys `self`
      — a model leak — and any array arg is unhashable or, worse,
      hashed by object id: a retrace hazard)
  G9  precision demotions (astype(float32), dd32 conversions,
      f32-typed literals, mixed f32 x f64 arithmetic) only at
      declared boundary sites (analysis/precision_registry.py), and
      no ops/dd consumer in the exact-precision modules may receive
      an f32-provenance value — the dataflow half lives in
      analysis/graftflow.py (lattice {dd, f64, f32, unknown} over
      analysis/cfg.py CFGs)
  G10 jit-traced code must not bake parameter VALUES as trace
      constants: in-trace .value/.quantity reads are legal only when
      covered by TimingModel._compile_key (str/bool/int kinds,
      presence checks, PLANET_SHAPIRO, frozen-guarded reads), and
      traced closures must not capture parameter-value-derived
      bindings from their builders (graftflow's pval taint pass,
      cross-checked against a live parse of _compile_key)
  G11 use-after-donate: a jit product built with donate_argnums
      consumes the buffers passed at those positions — the donated
      array is DELETED after the dispatch, so any later read of the
      same variable (without an intervening rebinding) is a runtime
      RuntimeError at best and, under pipelined dispatch, a race
      against XLA reusing the buffer for outputs. Lexical order
      approximates dominance (the same approximation class as
      G10's frozen-guard check); donated positions are read from the
      literal donate_argnums, a non-literal donates conservatively
      at every position (graftflow.check_g11_module)
  G12 supervised-dispatch call sites in the dispatch layer (the G6
      file set) must run under a tracer span context
      (``pint_tpu.obs.span``/``attach``): the supervisor's own
      dispatch span and its retry/timeout/breaker/failover children
      parent from the ambient context, so a dispatch issued with no
      span context is a causal orphan — its degradation events can
      never be traced back to the request/fit that caused them.
      Compliance is approximate like G10's frozen-guard check: the
      call must be lexically under a ``with ...span(...)`` /
      ``attach(...)``, or its enclosing function (or a lexical
      ancestor) must be reachable from a span-bearing function via
      same-module calls. Pragma/allowlist policy as G9.
  G13 no ad-hoc counter mutation in the dispatch/serve layer (the
      G6 dispatch file set): an attribute/dict INCREMENT on
      counter-named state (``*_count``/``*_total``/``*counter*`` or
      the serve/dispatch counter vocabulary — shed_*, submitted,
      timeouts, failovers, ...) bypasses the ``obs.metrics``
      registry (ISSUE 11), so the value would be invisible to
      /metrics, the SLO watchdog and the registry-vs-snapshot
      parity oracle. Mutate through a bound registry child
      (``.inc()``) or the owning class's ``bump()`` instead.
      Pragma/allowlist policy as G9.
  G14 health taps flow through ``HealthMonitor.observe`` (ISSUE 14):
      (a) ``pint_tpu_health_*`` registry metrics may be created/
      mutated ONLY inside pint_tpu/obs/health.py — a call site
      minting its own health counter/gauge forks the incident
      vocabulary away from the monitor's verdict/threshold/flight
      machinery; (b) in the dispatch layer (the G6 file set), a
      function that reads an in-trace health vector (an ``hv``-named
      binding or an "hv" signal key) must hand it to a
      ``.observe(...)`` call in the same function — ad-hoc host math
      on a health vector at the call site bypasses the validated
      thresholds, the registry recording, the span event and the
      incident/flight path all at once. Pragma/allowlist policy as
      G9.
  G15 profiler control and compile-cost probes only in the perf
      plane (ISSUE 15): ``jax.profiler.start_trace``/``stop_trace``
      and the ``.lower(...).compile()`` /
      ``.cost_analysis()``/``.memory_analysis()`` probe pattern may
      appear only in pint_tpu/obs/perf.py and pint_tpu/profiling.py
      — a raw trace call elsewhere bypasses the supervised, bounded,
      rate-limited window facility (and an unclosed trace poisons
      every later window), while an ad-hoc cost probe re-runs
      lower/compile outside the once-per-key ledger dedup and can
      land on a hot path. Route through
      ``obs.perf.request_window`` / ``obs.perf.note_compile``.
      Pragma/allowlist policy as G9.
  G16 lock discipline over the dispatch layer (the G6 file set) +
      runtime/ + obs/ + the serve CLI, against
      analysis/lock_registry.py (ISSUE 18; the dynamic mirror is
      ``runtime.locks`` under $PINT_TPU_LOCK_TRACE): (0) raw
      ``threading.Lock/RLock/Condition`` construction must go
      through the ``runtime.locks`` factories so the traced build
      sees every lock; (1) registry-GUARDED fields may be written
      only in ``__init__``, ``*_locked`` methods, declared holder
      methods, or lexically under ``with self.<lock>`` (or a
      declared alias like the Condition wrapping it); (2) registry
      SCRAPE_ROOTS (MetricsServer handlers, lock-free snapshot
      surfaces) must be statically unreachable from any ENGINE_LOCKS
      acquisition over the resolvable call graph — the repo-wide
      proof that a /metrics scrape never blocks on an engine lock;
      (3) no supervised dispatch, journal fsync/admit/ack, or host
      solve (BLOCKING_CALLS) lexically under ``with`` on an
      ENGINE_LOCKS attribute — the scheduler's ``_dispatch_lock``
      is deliberately unlisted (dispatch under it IS the drain
      design). Registry entries carry written justifications and
      stale entries fail the run; pragma/allowlist policy as G9.
  G17 no raw ``os.environ`` / ``os.getenv`` outside
      pint_tpu/config.py (ISSUE 18, finishing the ISSUE 11 ban):
      every env knob reads through a validated config parser
      (warn-and-ignore on bad values — the
      ``dispatch_rtt_override_ms`` pattern), so a typo'd value can
      never silently change production behavior. Whole-environment
      subprocess passthroughs (``env=dict(os.environ)``) forward
      rather than parse and are sanctioned per-site with a G17
      pragma. Pragma/allowlist policy as G7.

jit-reachability is inferred statically, seeded by project
conventions: any function whose early positional parameters include
``pv`` (the traced parameter-value dict every Component compute
method takes), any function named as an argument of jax.jit /
jax.vmap / jax.pmap / shard_map anywhere in the scanned tree, any
function decorated with a jit, and the transitive closure over
same-module calls (``self.helper(...)`` / ``helper(...)``) plus
lexical containment (closures defined inside a traced builder).

Suppression: a central allowlist (pint_tpu/analysis/allowlist.py,
every entry carries a written justification) or an inline pragma
``# graftlint: allow G<n> -- reason`` on the flagged line. Stale
allowlist entries are themselves errors, so the list cannot rot.

Run: ``python -m pint_tpu.analysis.graftlint [--root DIR] [--json]
[--format json] [--changed-only] [--no-dynamic]``. Exit 0 = clean.
``--format json`` emits one {file,line,rule,msg} record per line
(JSONL) for machines; ``--changed-only`` scopes findings to files
changed vs HEAD for fast pre-commit runs (tools/check.sh chains it
with the lint + fast pytest lanes). The repo-clean gate is
tests/test_graftlint.py::test_repo_clean (tier-1, `-m lint`).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

RULES = {
    "G1": "no scalar coercion of traced values in jit-reachable code",
    "G2": "no numpy host calls in models/ compute paths",
    "G3": "component class docstring must cite its reference",
    "G4": "every numeric parameter needs a param_dimensions spec",
    "G5": "linear-design claims paired and exercised by SINK_PAR",
    "G6": "TPU-touching invocations timeout-bounded; dispatch-layer "
          "jit calls route through the runtime supervisor",
    "G7": "jax.config.update only in sanctioned entry points",
    "G8": "no functools.lru_cache on methods",
    "G9": "precision demotions only at registered boundary sites; "
          "no f32-provenance value reaches the dd chain",
    "G10": "no parameter values baked as trace constants (reads and "
           "closure captures cross-checked against the compile key)",
    "G11": "no use-after-donate: a buffer passed in a donated "
           "argument position must not be read after the dispatch",
    "G12": "supervised-dispatch call sites must run under a tracer "
           "span context (obs.span/attach) so dispatch telemetry "
           "has a causal parent",
    "G13": "no ad-hoc counter mutation in the dispatch/serve layer "
           "outside the obs.metrics registry",
    "G14": "health taps read through HealthMonitor.observe: "
           "pint_tpu_health_* metrics only in obs/health.py, and "
           "dispatch-layer health vectors must reach an observe()",
    "G15": "jax.profiler trace control and lower().compile() cost "
           "probes only in obs/perf.py / profiling.py (the "
           "supervised window facility and the once-per-key "
           "compile ledger)",
    "G16": "lock discipline in the dispatch/serve/runtime/obs "
           "layers: locks constructed through runtime.locks "
           "factories, registry-guarded fields written only under "
           "their lock, scrape paths statically unreachable from "
           "engine-lock acquisition, and no dispatch/fsync/host "
           "solve under an engine lock "
           "(analysis/lock_registry.py)",
    "G17": "no raw os.environ/os.getenv outside pint_tpu/config.py "
           "— env knobs read through validated config parsers; "
           "subprocess whole-env passthroughs pragma-sanctioned",
}

# entry points allowed to mutate global jax config (G7): the package
# root (x64 contract), the config module (compile-cache knobs), and
# this linter's own CLI (it must pin the CPU platform before the
# dynamic zoo import, per the CLAUDE.md wedged-tunnel gotcha)
G7_SANCTIONED = {
    "pint_tpu/__init__.py",
    "pint_tpu/config.py",
    "pint_tpu/analysis/graftlint.py",
}

# component compute-path method convention: a traced function's early
# positional params include the pv dict (CLAUDE.md "Parameter VALUES
# are runtime args"); host methods never take pv
PV_PARAM = "pv"
PV_WINDOW = 3  # pv must appear among the first 3 positional params

JIT_WRAPPERS = {"jit", "vmap", "pmap", "shard_map", "jacfwd", "jacrev",
                "grad", "value_and_grad", "pallas_call"}

COERCIONS = {"float", "int", "bool", "complex"}
COERCION_METHODS = {"item", "tolist"}

NUMERIC_PARAM_CTORS = {"floatParameter", "MJDParameter",
                       "prefixParameter", "maskParameter",
                       "pairParameter", "AngleParameter", "floatParam"}

# abstract bases never instantiated by users (mirrors
# tests/test_all_components.py's abstract set)
ABSTRACT_COMPONENTS = {"Component", "DelayComponent", "PhaseComponent",
                       "NoiseComponent"}

# in-process jax calls that initialize a backend (and therefore hang
# forever on a wedged axon tunnel — CLAUDE.md environment gotchas)
BACKEND_TOUCHES = {"devices", "local_devices", "device_count",
                   "local_device_count", "default_backend"}
# a module that touches the backend in-process must probe first with
# one of these bounded helpers (bench.accelerator_responsive runs the
# init in a subprocess under a kill timer)
BOUNDED_PROBES = {"accelerator_responsive"}

SUBPROCESS_CALLS = {"run", "check_output", "check_call", "call"}

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\s+(G\d+)\s*(?:--|—|:)\s*(\S.*)")


@dataclass
class Violation:
    rule: str
    path: str        # repo-relative, forward slashes
    line: int
    msg: str
    snippet: str = ""
    # "file": anchored to one file's content; "repo": a repo-global
    # fact (stale allowlist/registry entries, dynamic zoo findings,
    # compile-key drift) that --changed-only must never filter away
    scope: str = "file"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{self.rule} {loc}: {self.msg}"
        if self.snippet:
            out += f"\n    {self.snippet.strip()}"
        return out


@dataclass
class LintReport:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Tuple[Violation, str]] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        return not self.violations


# --------------------------------------------------------------------
# file collection
# --------------------------------------------------------------------

def iter_lint_files(root: str):
    """(abspath, relpath) for every file graftlint owns: the package
    tree plus tools/ (G6 also reads the shell scripts there)."""
    skip_dirs = {"__pycache__", ".git", ".jax_compile_cache"}
    for sub in ("pint_tpu", "tools"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in sorted(filenames):
                if fn.endswith((".py", ".sh")):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, root).replace(os.sep, "/")


# --------------------------------------------------------------------
# per-module model
# --------------------------------------------------------------------

class ModuleInfo:
    """Parsed module + parent links + function/class indexes."""

    def __init__(self, relpath: str, src: str):
        self.relpath = relpath
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=relpath)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.functions: List[ast.FunctionDef] = []
        self.classes: List[ast.ClassDef] = []
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(node)
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
        self.by_name: Dict[str, List[ast.FunctionDef]] = {}
        for f in self.functions:
            self.by_name.setdefault(f.name, []).append(f)
        self.jit_funcs: Set[ast.FunctionDef] = set()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def in_jit_region(self, node: ast.AST) -> bool:
        cur = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            else self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and cur in self.jit_funcs:
                return True
            cur = self.parents.get(cur)
        return False


def _decorator_is_jit(dec: ast.AST) -> bool:
    """@jax.jit, @jit, @partial(jax.jit, ...), @functools.partial(jit)."""
    if isinstance(dec, ast.Call):
        f = dec.func
        if isinstance(f, (ast.Name, ast.Attribute)) and \
                _tail_name(f) == "partial":
            return any(_tail_name(a) == "jit" for a in dec.args
                       if isinstance(a, (ast.Name, ast.Attribute)))
        return _tail_name(f) == "jit"
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return _tail_name(dec) == "jit"
    return False


def _tail_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def collect_jit_seed_names(
        modules: List[ModuleInfo]) -> Dict[str, Set[str]]:
    """relpath -> function NAMES passed (possibly nested, e.g.
    jax.jit(jax.vmap(_solve_one))) to a jit wrapper. Names harvested
    in a module seed that module; names that follow the _private
    convention additionally seed every module (cross-module case:
    serve/bucket.py jits parallel.pta._solve_one, so _solve_one's
    body is traced though pta.py never calls jax.jit on it). Public
    names deliberately do NOT cross modules — `chi2`/`f` collide with
    unrelated host helpers everywhere."""
    per_module: Dict[str, Set[str]] = {}
    global_private: Set[str] = set()

    def harvest(call: ast.Call, names: Set[str]):
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(a, (ast.Name, ast.Attribute)):
                t = _tail_name(a)
                if t and not t.startswith("jax"):
                    names.add(t)
            elif isinstance(a, ast.Call):
                f = a.func
                # see through nesting (jit(vmap(f))) AND partial
                # binding (pallas_call(partial(_kernel, m), ...)) —
                # the bound function's body is traced either way
                if _tail_name(f) in JIT_WRAPPERS or \
                        _tail_name(f) == "partial":
                    harvest(a, names)

    for m in modules:
        names: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    _tail_name(node.func) in JIT_WRAPPERS:
                harvest(node, names)
        names -= JIT_WRAPPERS
        per_module[m.relpath] = names
        global_private |= {n for n in names if n.startswith("_")}
    for relpath in per_module:
        per_module[relpath] |= global_private
    return per_module


def mark_jit_regions(m: ModuleInfo, global_seed_names: Set[str]):
    """Seed + fixpoint propagation of jit-reachability (module doc)."""
    jit: Set[ast.FunctionDef] = set()
    for f in m.functions:
        args = [a.arg for a in f.args.args[:PV_WINDOW + 1]]
        if PV_PARAM in args:
            jit.add(f)
        if any(_decorator_is_jit(d) for d in f.decorator_list):
            jit.add(f)
        if f.name in global_seed_names:
            jit.add(f)
    # propagate: calls from jit bodies to same-module functions, by
    # bare name or self./cls. attribute — but a callee name locally
    # bound in the caller (parameter, assignment, loop target) is a
    # local callable, NOT the module function of the same name
    changed = True
    while changed:
        changed = False
        for f in list(jit):
            local = _locally_bound_names(f)
            for node in ast.walk(f):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                fn = node.func
                if isinstance(fn, ast.Name):
                    if fn.id in local:
                        continue
                    callee = fn.id
                elif isinstance(fn, ast.Attribute) and \
                        isinstance(fn.value, ast.Name) and \
                        fn.value.id in ("self", "cls"):
                    callee = fn.attr
                if callee is None:
                    continue
                for g in m.by_name.get(callee, []):
                    if g not in jit:
                        jit.add(g)
                        changed = True
    m.jit_funcs = jit


def _locally_bound_names(f: ast.FunctionDef) -> Set[str]:
    """Names bound inside ``f`` (params, assignments, loop/with/comp
    targets) — shadowing any same-named module function."""
    out = {a.arg for a in f.args.args + f.args.kwonlyargs}
    out.update(a.arg for a in (f.args.vararg, f.args.kwarg) if a)
    for node in ast.walk(f):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.For, ast.AsyncFor)):
            targets = [node.target]
        elif isinstance(node, ast.comprehension):
            targets = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            targets = [i.optional_vars for i in node.items
                       if i.optional_vars is not None]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


# --------------------------------------------------------------------
# G1 / G2 — coercions and numpy in traced code
# --------------------------------------------------------------------

HOST_ATTRS = {"value", "uncertainty", "frozen", "index", "units",
              "name", "prefix", "ndim", "size", "ref_day"}
HOST_ROOT_MODULES = {"math", "os", "sys"}
# frozen_trace_value is the sanctioned host read of a frozen param
# (models/timing_model.py — raises on a free param, compile-keyed
# otherwise), so coercing ITS result is host arithmetic, not a
# traced-value coercion
HOST_CALLS = {"len", "str", "repr", "ord", "range",
              "frozen_trace_value"}


def _is_host_expr(node: ast.AST) -> bool:
    """Conservatively: does this expression provably involve only
    host (non-traced) data? Unknown names are NOT host — traced
    arrays flow through locals."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in HOST_ATTRS:
            return True
        return _root_name(node) in HOST_ROOT_MODULES
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in HOST_CALLS:
            return True
        if isinstance(f, ast.Attribute) and \
                _root_name(f) in HOST_ROOT_MODULES:
            return True
        return False
    if isinstance(node, ast.BinOp):
        return _is_host_expr(node.left) and _is_host_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_host_expr(node.operand)
    if isinstance(node, ast.Subscript):
        return _is_host_expr(node.value)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_host_expr(e) for e in node.elts)
    if isinstance(node, ast.BoolOp):
        return all(_is_host_expr(v) for v in node.values)
    if isinstance(node, ast.IfExp):
        return _is_host_expr(node.body) and _is_host_expr(node.orelse)
    return False


def check_g1(m: ModuleInfo) -> List[Violation]:
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call) or not m.in_jit_region(node):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in COERCIONS:
            if node.args and _is_host_expr(node.args[0]):
                continue
            out.append(Violation(
                "G1", m.relpath, node.lineno,
                f"{fn.id}() inside jit-reachable "
                f"{_region_name(m, node)} coerces a potentially "
                f"traced value to a Python scalar (device sync or "
                f"trace constant)", m.line_text(node.lineno)))
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in COERCION_METHODS:
            out.append(Violation(
                "G1", m.relpath, node.lineno,
                f".{fn.attr}() inside jit-reachable "
                f"{_region_name(m, node)} forces a host sync on a "
                f"potentially traced array", m.line_text(node.lineno)))
    return out


def _region_name(m: ModuleInfo, node: ast.AST) -> str:
    f = m.enclosing_function(node)
    return f"`{f.name}`" if f is not None else "module code"


def check_g2(m: ModuleInfo) -> List[Violation]:
    if "/models/" not in "/" + m.relpath:
        return []
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call) or not m.in_jit_region(node):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                _root_name(fn) in ("np", "numpy"):
            out.append(Violation(
                "G2", m.relpath, node.lineno,
                f"numpy call np.{fn.attr}() inside jit-reachable "
                f"{_region_name(m, node)}: on a tracer this is a "
                f"host fallback (breaks jit) or a late error",
                m.line_text(node.lineno)))
    return out


# --------------------------------------------------------------------
# G3 / G4(static) / G5(static) — the component zoo, via a global
# class graph (components subclass bases imported from other modules)
# --------------------------------------------------------------------

class ClassGraph:
    def __init__(self, modules: List[ModuleInfo]):
        self.defs: Dict[str, Tuple[ModuleInfo, ast.ClassDef]] = {}
        for m in modules:
            for c in m.classes:
                self.defs.setdefault(c.name, (m, c))
        self.component_classes = self._closure("Component")

    def _closure(self, root: str) -> Set[str]:
        comp = {root}
        changed = True
        while changed:
            changed = False
            for name, (m, c) in self.defs.items():
                if name in comp:
                    continue
                bases = {b.id if isinstance(b, ast.Name)
                         else _tail_name(b) for b in c.bases}
                if bases & comp:
                    comp.add(name)
                    changed = True
        return comp

    def is_registered_component(self, name: str) -> bool:
        if name not in self.component_classes or \
                name in ABSTRACT_COMPONENTS or name.startswith("_"):
            return False
        m, c = self.defs[name]
        for node in c.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "register" \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is False:
                        return False
        return True

    def defines_in_body(self, name: str, method: str) -> bool:
        m, c = self.defs[name]
        return any(isinstance(n, ast.FunctionDef) and n.name == method
                   for n in c.body)

    def ancestors(self, name: str) -> List[str]:
        out, todo = [], [name]
        while todo:
            cur = todo.pop()
            if cur not in self.defs:
                continue
            _, c = self.defs[cur]
            for b in c.bases:
                bn = b.id if isinstance(b, ast.Name) else _tail_name(b)
                if bn and bn not in out:
                    out.append(bn)
                    todo.append(bn)
        return out

    def defines_or_inherits(self, name: str, method: str) -> bool:
        for cand in [name] + self.ancestors(name):
            if cand == "Component":
                continue  # the base's empty default doesn't count
            if cand in self.defs and self.defines_in_body(cand, method):
                return True
        return False


def _registers_numeric_params(graph: ClassGraph, name: str) -> bool:
    """Does this class (or an ancestor) construct numeric Parameter
    objects anywhere in its body (init, setup, add_* helpers)?"""
    for cand in [name] + graph.ancestors(name):
        if cand not in graph.defs or cand == "Component":
            continue
        _, c = graph.defs[cand]
        for node in ast.walk(c):
            if isinstance(node, ast.Call) and \
                    _tail_name(node.func) in NUMERIC_PARAM_CTORS:
                return True
    return False


def check_g3(graph: ClassGraph) -> List[Violation]:
    out = []
    for name, (m, c) in sorted(graph.defs.items()):
        if not graph.is_registered_component(name):
            continue
        doc = ast.get_docstring(c) or ""
        if not re.search(r"[Rr]eference", doc):
            out.append(Violation(
                "G3", m.relpath, c.lineno,
                f"component {name} does not cite its reference "
                f"file/symbol in the class docstring "
                f"(CLAUDE.md convention)", f"class {name}(...):"))
    return out


def check_g4_static(graph: ClassGraph) -> List[Violation]:
    out = []
    for name, (m, c) in sorted(graph.defs.items()):
        if not graph.is_registered_component(name):
            continue
        if not _registers_numeric_params(graph, name):
            continue
        if not graph.defines_or_inherits(name, "param_dimensions"):
            out.append(Violation(
                "G4", m.relpath, c.lineno,
                f"component {name} registers numeric parameters but "
                f"neither defines nor inherits a param_dimensions() "
                f"spec (units go dimension-unchecked)",
                f"class {name}(...):"))
    return out


def check_g5_static(graph: ClassGraph) -> List[Violation]:
    out = []
    for name, (m, c) in sorted(graph.defs.items()):
        if name not in graph.component_classes or name == "Component":
            continue
        has_names = graph.defines_in_body(name, "linear_design_names")
        has_local = graph.defines_in_body(name, "linear_design_local")
        if has_names != has_local:
            missing = ("linear_design_local" if has_names
                       else "linear_design_names")
            out.append(Violation(
                "G5", m.relpath, c.lineno,
                f"component {name} defines one hybrid-Jacobian hook "
                f"but not {missing}: claims and columns must be "
                f"declared together", f"class {name}(...):"))
    return out


# --------------------------------------------------------------------
# G6 — timeout bounds in tools/ and scripts/
# --------------------------------------------------------------------

def _g6_applies(relpath: str) -> bool:
    return relpath.startswith("tools/") or "/scripts/" in relpath


# the production dispatch layer: every device call here must route
# through pint_tpu.runtime.DispatchSupervisor (runtime/ itself is the
# supervisor — exempt by construction). Host-side exploration tools
# (mcmc, bayesian, templates, gridutils, pintk) are deliberately
# outside the set: they are interactive analysis surfaces, not the
# serving/fitting path the north star load-bears on.
G6_DISPATCH_FILES = {"pint_tpu/fitter.py", "pint_tpu/gls.py",
                     "pint_tpu/wideband_fitter.py",
                     "pint_tpu/config.py"}
G6_DISPATCH_DIRS = ("pint_tpu/serve/", "pint_tpu/parallel/",
                    "pint_tpu/sampling/", "pint_tpu/pta/")


def _g6_dispatch_applies(relpath: str) -> bool:
    if relpath.startswith("pint_tpu/runtime/"):
        return False
    return relpath in G6_DISPATCH_FILES or \
        relpath.startswith(G6_DISPATCH_DIRS)


def collect_jit_products(modules: List[ModuleInfo]):
    """Names bound to jit PRODUCTS (callables whose invocation is a
    device dispatch): assignment targets of a jit(...) call —
    including ``self.x = jax.jit(...)`` attributes — and functions
    decorated with a jit. ``pta.shard.compile_with_plan(...)``
    products count too: a plan IS a jitted executable (plain or
    shard_map-wrapped), so calling one directly is the same
    unsupervised dispatch. Private names are shared across modules
    (wideband_fitter imports gls's _gls_kernel); public names stay
    module-local, same convention as the jit-reachability seeds."""
    per_module: Dict[str, Set[str]] = {}
    global_private: Set[str] = set()
    for m in modules:
        names: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _tail_name(node.value.func) in (
                        "jit", "compile_with_plan"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
        for f in m.functions:
            if any(_decorator_is_jit(d) for d in f.decorator_list):
                names.add(f.name)
        per_module[m.relpath] = names
        global_private |= {n for n in names if n.startswith("_")}
    return per_module, global_private


def check_g6_dispatch(m: ModuleInfo,
                      products: Set[str]) -> List[Violation]:
    """Dispatch-layer half of G6: direct CALLS of jit products bypass
    the runtime supervisor's watchdog/breaker/failover policy — on a
    wedged axon tunnel that is an unbounded hang. Passing the product
    as an argument (supervisor.dispatch(kernel, ...)) is the
    sanctioned route and is not a call, so it never flags."""
    if not _g6_dispatch_applies(m.relpath):
        return []
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Call) and \
                _tail_name(fn.func) == "jit":
            out.append(Violation(
                "G6", m.relpath, node.lineno,
                "immediate jax.jit(...)(...) dispatch in the "
                "supervised dispatch layer bypasses the runtime "
                "supervisor (watchdog/breaker/failover) — route it "
                "through DispatchSupervisor.dispatch",
                m.line_text(node.lineno)))
            continue
        tail = _tail_name(fn)
        if tail not in products:
            continue
        # flag bare names AND any attribute chain ending in a product
        # name (self._gls, engine.cache._gls, ...) — a known limit:
        # a local alias (k = self._k; k(x)) escapes this static
        # check, same approximation class as the jit-reachability
        # inference
        if isinstance(fn, (ast.Name, ast.Attribute)):
            out.append(Violation(
                "G6", m.relpath, node.lineno,
                f"direct call of jit product `{tail}` in the "
                f"supervised dispatch layer bypasses the runtime "
                f"supervisor (unbounded hang on a wedged tunnel) — "
                f"pass it to DispatchSupervisor.dispatch instead",
                m.line_text(node.lineno)))
    return out


# G12 — span context at supervised-dispatch call sites ---------------

# context managers that establish a span context (pint_tpu.obs):
# span()/open_span() enter a new span, attach() re-enters a captured
# one on a worker thread — all three parent subsequent dispatch spans
SPAN_CONTEXT_CALLS = {"span", "attach"}
DISPATCH_METHODS = {"dispatch", "dispatch_async"}
# receiver-name markers identifying the callee as the runtime
# supervisor (sup.dispatch / self.supervisor.dispatch /
# get_supervisor().dispatch / supervisor.dispatch_async)
SUPERVISOR_MARKERS = {"supervisor", "sup", "get_supervisor"}


def _expr_names(node: ast.AST) -> Set[str]:
    """Every Name id / Attribute attr / called tail in an expression
    — how a dispatch call's receiver chain is matched against the
    supervisor markers."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _with_establishes_span(node) -> bool:
    return isinstance(node, (ast.With, ast.AsyncWith)) and any(
        isinstance(it.context_expr, ast.Call)
        and _tail_name(it.context_expr.func) in SPAN_CONTEXT_CALLS
        for it in node.items)


def _span_context_closure(m: ModuleInfo) -> Set[ast.FunctionDef]:
    """Functions that (approximately) run under a span context:
    seeds are functions whose body contains a with-span/with-attach
    statement; the closure propagates along same-module calls (bare
    name or self./cls. attribute) from a seed to its callees — the
    fit_toas -> _fit_device pattern — with the same shadowed-local
    filtering as the jit-reachability inference."""
    seeds: Set[ast.FunctionDef] = set()
    for f in m.functions:
        for node in ast.walk(f):
            if _with_establishes_span(node):
                seeds.add(f)
                break
    ok = set(seeds)
    changed = True
    while changed:
        changed = False
        for f in list(ok):
            local = _locally_bound_names(f)
            for node in ast.walk(f):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                fn = node.func
                if isinstance(fn, ast.Name):
                    if fn.id in local:
                        continue
                    callee = fn.id
                elif isinstance(fn, ast.Attribute) and \
                        isinstance(fn.value, ast.Name) and \
                        fn.value.id in ("self", "cls"):
                    callee = fn.attr
                if callee is None:
                    continue
                for g in m.by_name.get(callee, []):
                    if g not in ok:
                        ok.add(g)
                        changed = True
    return ok


def check_g12(m: ModuleInfo) -> List[Violation]:
    """Span context at supervised-dispatch call sites (module
    docstring G12). Same file set as G6's dispatch half; runtime/
    is exempt by construction (the supervisor IS the span emitter).
    """
    if not _g6_dispatch_applies(m.relpath):
        return []
    closure = None  # computed lazily — most modules have no dispatch
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in DISPATCH_METHODS):
            continue
        if not (_expr_names(fn.value) & SUPERVISOR_MARKERS):
            continue
        # (a) lexically under a with-span/with-attach
        cur = m.parents.get(node)
        enclosed = False
        while cur is not None:
            if _with_establishes_span(cur):
                enclosed = True
                break
            cur = m.parents.get(cur)
        if enclosed:
            continue
        # (b) enclosing function (or a lexical ancestor — closures
        # the span-bearing function builds) in the span closure
        if closure is None:
            closure = _span_context_closure(m)
        cur = m.enclosing_function(node)
        in_closure = False
        while cur is not None:
            if cur in closure:
                in_closure = True
                break
            cur = m.enclosing_function(cur)
        if in_closure:
            continue
        out.append(Violation(
            "G12", m.relpath, node.lineno,
            f"supervised dispatch `{fn.attr}` with no span context: "
            f"the dispatch span (and its retry/timeout/breaker/"
            f"failover children) would be a causal orphan — wrap the "
            f"call site in `with obs.span(...)` (or obs.attach on a "
            f"worker thread)", m.line_text(node.lineno)))
    return out


# G13 — ad-hoc counter mutation outside obs.metrics ------------------

# the counter vocabulary of the serve/dispatch stack: every name
# that is (or was) a counter in the supervisor / serve metrics /
# admission / router / bucket-stats / AOT-store snapshot blocks.
# Kept explicit so a NEW counter name must be added here when its
# class grows one — at which point the rule starts protecting it.
G13_COUNTER_NAMES = frozenset({
    # runtime supervisor
    "dispatches", "guarded", "retries", "timeouts",
    "transient_errors", "failovers", "breaker_rejections",
    "breaker_recoveries", "abandoned_workers", "rtt_remeasures",
    "async_dispatches",
    # serve engine
    "submitted", "completed", "rejected", "failed",
    "deadline_missed", "fallback_single",
    # admission
    "shed_expired", "shed_deadline", "shed_quota", "shed_overload",
    "shed_shutdown", "shed_bursts", "injected_overload",
    "admitted", "shed", "acked",
    # router pools
    "demotions", "requests", "rows",
    # bucket stats
    "batches", "slots", "rows_real", "rows_padded",
    # AOT store / journal / flight
    "exported", "restored", "export_errors", "restore_errors",
    "hits", "misses", "replayed", "compactions", "dumps",
    "suppressed",
    # streaming GLS / append serving (ISSUE 12)
    "chunk_dispatches", "cg_solves", "cold_builds", "rank_updates",
    # numerical health (ISSUE 14)
    "health_incidents", "shadow_replays", "shadow_drift_exceeded",
    "cg_budget_exhausted",
    # array GWB likelihood plane (ISSUE 17)
    "gwb_solves", "block_assemblies", "hd_outer_solves",
    # serve fleet / journal hardening (ISSUE 19)
    "rehomed", "lease_expiries", "worker_kills", "heartbeats",
    "torn_records",
})


def _g13_counterish(name: Optional[str]) -> bool:
    if not name:
        return False
    n = name.lstrip("_")
    return (n in G13_COUNTER_NAMES or n.endswith("_count")
            or n.endswith("_total") or "counter" in n)


def _g13_target_name(tgt: ast.AST) -> Optional[str]:
    """The counter-ish name an increment target resolves to:
    ``x.timeouts`` -> "timeouts"; ``d["shed"]`` -> "shed";
    ``self.counters[k]`` -> "counters" (the container name)."""
    if isinstance(tgt, ast.Attribute):
        return tgt.attr
    if isinstance(tgt, ast.Subscript):
        sl = tgt.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            if _g13_counterish(sl.value):
                return sl.value
        return _tail_name(tgt.value)
    return None


def check_g13(m: ModuleInfo) -> List[Violation]:
    """Ad-hoc counter mutation in the dispatch/serve layer (module
    docstring G13): ``x.failovers += 1`` / ``d["shed"] += 1`` /
    ``x.timeouts = x.timeouts + 1`` on counter-named state bypasses
    the obs.metrics registry. Plain local names are never flagged
    (loop tallies are not metrics), and only the G6 dispatch file
    set is in scope — obs/ and runtime/ are the plane itself."""
    if not _g6_dispatch_applies(m.relpath):
        return []
    out = []
    for node in ast.walk(m.tree):
        tgt = None
        if isinstance(node, ast.AugAssign) and \
                isinstance(node.op, ast.Add):
            tgt = node.target
        elif isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.value, ast.BinOp) and \
                isinstance(node.value.op, ast.Add):
            # x.attr = x.attr + n / d[k] = d.get(k, 0) + n — flag
            # only the SELF-REFERENTIAL form (a fresh assignment of
            # a sum is not an increment)
            cand = node.targets[0]
            td = ast.unparse(cand)  # unparse: Load/Store ctx-blind
            selfref = any(
                (isinstance(sub, (ast.Attribute, ast.Subscript))
                 and ast.unparse(sub) == td) or (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "get"
                    and isinstance(cand, ast.Subscript)
                    and ast.unparse(sub.func.value)
                    == ast.unparse(cand.value))
                for sub in ast.walk(node.value))
            if selfref:
                tgt = cand
        if tgt is None or isinstance(tgt, ast.Name):
            continue
        name = _g13_target_name(tgt)
        if not _g13_counterish(name):
            continue
        out.append(Violation(
            "G13", m.relpath, node.lineno,
            f"ad-hoc increment of counter state `{name}` in the "
            f"dispatch/serve layer bypasses the obs.metrics "
            f"registry (invisible to /metrics, the SLO watchdog "
            f"and the parity oracle) — mutate through a bound "
            f"registry child (.inc()) or the owning bump()",
            m.line_text(node.lineno)))
    return out


# G14 — health taps flow through HealthMonitor.observe --------------

# the registry factory calls a stray health metric would ride
_G14_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_G14_PREFIX = "pint_tpu_health_"


def _g14_hv_name(name: Optional[str]) -> bool:
    return bool(name) and (name == "hv" or name.startswith("hv_"))


def check_g14(m: ModuleInfo) -> List[Violation]:
    """Health-tap routing (module docstring G14). Two halves:

    (a) repo-wide except obs/health.py itself:
    ``om.counter("pint_tpu_health_...")`` (or gauge/histogram)
    anywhere else — health.py's obs/ SIBLINGS included — mints a
    health metric the monitor's verdict machinery never sees;

    (b) dispatch layer only: a function binding/reading an ``hv``
    health vector must call ``.observe(...)`` somewhere in its body
    (the lexical approximation class of G10's frozen-guard check —
    a vector handed to a helper that observes escapes it, same as
    every other rule's known aliasing limit)."""
    out = []
    if m.relpath != "pint_tpu/obs/health.py":
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            if _tail_name(node.func) not in _G14_METRIC_FACTORIES:
                continue
            for a in node.args[:1]:
                if isinstance(a, ast.Constant) and \
                        isinstance(a.value, str) and \
                        a.value.startswith(_G14_PREFIX):
                    out.append(Violation(
                        "G14", m.relpath, node.lineno,
                        f"health metric {a.value!r} created outside "
                        f"pint_tpu/obs/health.py: the monitor's "
                        f"thresholds/incident/flight machinery never "
                        f"sees it — record through "
                        f"HealthMonitor.observe instead",
                        m.line_text(node.lineno)))
    if not _g6_dispatch_applies(m.relpath):
        return out
    for f in m.functions:
        if m.in_jit_region(f):
            # the PRODUCER side: in-trace kernels build the hv —
            # traced code cannot (and must not) call observe
            continue
        uses_hv = False
        observes = False
        todo = [f]
        while todo:
            cur = todo.pop()
            for node in ast.iter_child_nodes(cur):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node is not f and node in m.jit_funcs:
                    continue  # nested PRODUCER kernel: in-trace hv
                todo.append(node)
                if isinstance(node, ast.Name) and \
                        _g14_hv_name(node.id):
                    uses_hv = True
                elif isinstance(node, ast.Constant) and \
                        node.value == "hv":
                    uses_hv = True
                elif isinstance(node, ast.Call) and \
                        _tail_name(node.func) == "observe":
                    observes = True
        if uses_hv and not observes:
            # closure pattern: a nested dispatch closure may hand
            # the vector back to its builder, which observes — a
            # lexical ancestor's observe covers it (the G12
            # ancestor-closure approximation)
            cur = m.enclosing_function(f)
            while cur is not None and not observes:
                observes = any(
                    isinstance(n, ast.Call)
                    and _tail_name(n.func) == "observe"
                    for n in ast.walk(cur))
                cur = m.enclosing_function(cur)
        if uses_hv and not observes:
            out.append(Violation(
                "G14", m.relpath, f.lineno,
                f"`{f.name}` reads an in-trace health vector (hv) "
                f"without routing it through HealthMonitor.observe "
                f"— ad-hoc host math at the call site bypasses the "
                f"validated thresholds, registry recording, span "
                f"event and incident path",
                m.line_text(f.lineno)))
    return out


# G15 — profiler/cost probes only in the perf plane ------------------

# the two sanctioned homes: the window facility + the unmanaged
# script-scoped trace() wrapper it documents
G15_SANCTIONED = {"pint_tpu/obs/perf.py", "pint_tpu/profiling.py"}
_G15_TRACE_CALLS = {"start_trace", "stop_trace"}
_G15_COST_CALLS = {"cost_analysis", "memory_analysis"}


def check_g15(m: ModuleInfo) -> List[Violation]:
    """Profiler control + compile-cost probes confined to the perf
    plane (module docstring G15). Repo-wide minus the sanctioned
    files: a stray ``jax.profiler.start_trace`` in the serve layer
    bypasses the bounded/rate-limited window facility, and an ad-hoc
    ``.lower(...).compile()``/``.cost_analysis()`` probe escapes the
    once-per-key ledger dedup."""
    if m.relpath in G15_SANCTIONED:
        return []
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        tail = fn.attr
        if tail in _G15_TRACE_CALLS and \
                "profiler" in _expr_names(fn.value):
            out.append(Violation(
                "G15", m.relpath, node.lineno,
                f"raw jax.profiler.{tail}() outside the perf plane: "
                f"an unmanaged trace bypasses the supervised, "
                f"bounded, rate-limited window facility — use "
                f"obs.perf.request_window (or profiling.trace for "
                f"script-scoped attribution runs)",
                m.line_text(node.lineno)))
        elif tail in _G15_COST_CALLS:
            out.append(Violation(
                "G15", m.relpath, node.lineno,
                f".{tail}() cost probe outside the perf plane: "
                f"probe through obs.perf.note_compile/cost_probe so "
                f"the lower/compile runs once per key (ledger "
                f"dedup), never on a hot path",
                m.line_text(node.lineno)))
        elif tail == "compile" and isinstance(fn.value, ast.Call) \
                and _tail_name(fn.value.func) == "lower":
            out.append(Violation(
                "G15", m.relpath, node.lineno,
                ".lower(...).compile() probe outside the perf "
                "plane: route through obs.perf.note_compile/"
                "cost_probe (once-per-key ledger dedup)",
                m.line_text(node.lineno)))
    return out


def check_g6_python(m: ModuleInfo) -> List[Violation]:
    """Timeout bounds in tools//scripts Python. The bounded-probe
    requirement is module-wide and order-insensitive — a deliberate
    approximation (static order is undecidable across call paths);
    the probe's presence is what reviews anchor on."""
    if not _g6_applies(m.relpath):
        return []
    out = []
    has_probe = any(
        isinstance(n, ast.Call) and _tail_name(n.func) in BOUNDED_PROBES
        for n in ast.walk(m.tree))
    # `from subprocess import run [as r]` aliases
    sub_aliases: Dict[str, str] = {}
    for n in ast.walk(m.tree):
        if isinstance(n, ast.ImportFrom) and n.module == "subprocess":
            for a in n.names:
                sub_aliases[a.asname or a.name] = a.name
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        tail = _tail_name(fn)
        sub_call = None
        if isinstance(fn, ast.Attribute) and \
                _root_name(fn) == "subprocess":
            sub_call = tail
        elif isinstance(fn, ast.Name) and fn.id in sub_aliases:
            sub_call = sub_aliases[fn.id]
        if sub_call == "Popen":
            out.append(Violation(
                "G6", m.relpath, node.lineno,
                "subprocess.Popen has no timeout bound of its own "
                "(.wait() hangs on a wedged tunnel) — use "
                "subprocess.run(timeout=...)",
                m.line_text(node.lineno)))
        elif sub_call in SUBPROCESS_CALLS:
            if not any(kw.arg == "timeout" for kw in node.keywords):
                out.append(Violation(
                    "G6", m.relpath, node.lineno,
                    f"subprocess.{sub_call}() without timeout=: a "
                    f"wedged axon tunnel hangs the child forever",
                    m.line_text(node.lineno)))
        elif isinstance(fn, ast.Attribute) and \
                _root_name(fn) == "jax" and tail in BACKEND_TOUCHES:
            if not has_probe:
                out.append(Violation(
                    "G6", m.relpath, node.lineno,
                    f"in-process jax.{tail}() with no bounded probe "
                    f"in this module: a wedged tunnel hangs backend "
                    f"init with no error (probe first with "
                    f"bench.accelerator_responsive)",
                    m.line_text(node.lineno)))
    return out


def check_g6_shell(relpath: str, src: str) -> List[Violation]:
    """Every python invocation in a tools/ shell script must be
    timeout-bounded: in this container every `python` imports jax via
    sitecustomize, and backend init hangs on a wedged tunnel."""
    if not _g6_applies(relpath):
        return []
    out = []
    # join backslash continuations first — `timeout N \` + `python ...`
    # is one bounded command, not a bare python line
    joined: List[Tuple[int, str]] = []
    pending: Optional[Tuple[int, str]] = None
    for i, raw in enumerate(src.splitlines(), 1):
        if pending is not None:
            start, acc = pending
            merged = acc + " " + raw.strip()
        else:
            start, merged = i, raw
        if merged.rstrip().endswith("\\"):
            pending = (start, merged.rstrip()[:-1])
        else:
            pending = None
            joined.append((start, merged))
    if pending is not None:
        joined.append(pending)
    for i, line in joined:
        code = line.split("#", 1)[0]
        if re.search(r"\bpython3?\b", code) and \
                not re.search(r"\btimeout\b", code):
            out.append(Violation(
                "G6", relpath, i,
                "python invocation without a `timeout` bound "
                "(wedged tunnels hang, they do not error)", line))
    return out


# --------------------------------------------------------------------
# G7 / G8
# --------------------------------------------------------------------

def check_g7(m: ModuleInfo) -> List[Violation]:
    if m.relpath in G7_SANCTIONED:
        return []
    # `from jax import config` makes a bare config.update(...) the
    # same process-global mutation — track the import form too
    bare_config_is_jax = any(
        isinstance(n, ast.ImportFrom) and n.module == "jax"
        and any(a.name == "config" for a in n.names)
        for n in ast.walk(m.tree))
    out = []
    for node in ast.walk(m.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"):
            continue
        target = node.func.value
        is_jax_config = (
            isinstance(target, ast.Attribute)
            and target.attr == "config"
            and _root_name(node.func) == "jax") or (
            bare_config_is_jax and isinstance(target, ast.Name)
            and target.id == "config")
        if is_jax_config:
            out.append(Violation(
                "G7", m.relpath, node.lineno,
                "jax.config.update() outside sanctioned entry points "
                "(pint_tpu/__init__.py, pint_tpu/config.py): global "
                "config flips affect every other caller in-process",
                m.line_text(node.lineno)))
    return out


def check_g8(m: ModuleInfo) -> List[Violation]:
    out = []
    for f in m.functions:
        if m.enclosing_class(f) is None:
            continue
        args = f.args.args
        if not args or args[0].arg not in ("self", "cls"):
            continue
        for dec in f.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _tail_name(target) in ("lru_cache", "cache") and \
                    (_root_name(target) in ("functools", None) or
                     isinstance(target, ast.Name)):
                out.append(Violation(
                    "G8", m.relpath, f.lineno,
                    f"functools.{_tail_name(target)} on method "
                    f"`{f.name}`: caches `self` (leak) and hashes "
                    f"array args by id (retrace hazard) — use an "
                    f"explicit keyed cache like _get_compiled",
                    m.line_text(f.lineno)))
    return out


# --------------------------------------------------------------------
# dynamic (import-the-zoo) half of G4 / G5
# --------------------------------------------------------------------

def _load_sink_par(root: str) -> Optional[str]:
    p = os.path.join(root, "tests", "test_all_components.py")
    if not os.path.exists(p):
        return None
    mobj = re.search(r'SINK_PAR = """(.*?)"""',
                     open(p).read(), re.S)
    return mobj.group(1) if mobj else None


def dynamic_registry_checks(root: str) -> List[Violation]:
    """Imports the full component zoo (CPU-pinned) and checks G4
    coverage + G5 exercise against the committed SINK_PAR. Separated
    so tests can run the AST half without touching jax."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
    except RuntimeError:
        pass  # backend already initialized by the host process
    import warnings

    import pint_tpu.models  # noqa: F401 — registry side effects
    import pint_tpu.models.binary  # noqa: F401
    import pint_tpu.models.components_extra  # noqa: F401
    import pint_tpu.models.components_tail  # noqa: F401
    import pint_tpu.models.noise  # noqa: F401
    import pint_tpu.models.tcb_conversion  # noqa: F401
    from pint_tpu.models.timing_model import component_types

    out: List[Violation] = []
    out += check_g4_dynamic(component_types)
    sink = _load_sink_par(root)
    if sink is None:
        out.append(Violation(
            "G5", "tests/test_all_components.py", 0,
            "SINK_PAR not found — the kitchen-sink sweep that "
            "exercises hybrid-Jacobian claims is missing"))
        return out
    import io

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.models import get_model

        model = get_model(io.StringIO(sink))
    out += check_g4_sink(model)
    out += check_g5_dynamic(component_types, model)
    return out


def _numeric_param_types():
    from pint_tpu.models.parameter import (
        AngleParameter,
        MJDParameter,
        floatParameter,
        maskParameter,
        pairParameter,
        prefixParameter,
    )

    return (floatParameter, MJDParameter, prefixParameter,
            maskParameter, pairParameter, AngleParameter)


def check_g4_dynamic(component_types: dict) -> List[Violation]:
    """Bare-instance coverage: every numeric parameter registered at
    construction must resolve through _spec_lookup."""
    from pint_tpu.units import _spec_lookup

    NUM = _numeric_param_types()
    out = []
    for name, cls in sorted(component_types.items()):
        if name in ABSTRACT_COMPONENTS:
            continue
        comp = cls()
        spec = comp.param_dimensions()
        missing = [p.name for p in comp.params.values()
                   if isinstance(p, NUM) and
                   _spec_lookup(spec, p.name) is None]
        if missing:
            out.append(Violation(
                "G4", _class_path(cls), 0,
                f"{name}.param_dimensions() does not cover "
                f"{missing} — units go dimension-unchecked"))
    return out


def check_g4_sink(model) -> List[Violation]:
    """SINK-model coverage: prefix/mask families only materialize at
    par parse, so the bare-instance check misses them."""
    from pint_tpu.models.parameter import (
        boolParameter,
        intParameter,
        strParameter,
    )
    from pint_tpu.units import _spec_lookup

    out = []
    for cname, comp in model.components.items():
        spec = comp.param_dimensions()
        missing = [p.name for p in comp.params.values()
                   if not isinstance(p, (strParameter, boolParameter,
                                         intParameter))
                   and _spec_lookup(spec, p.name) is None]
        if missing:
            out.append(Violation(
                "G4", _class_path(type(comp)), 0,
                f"{cname}.param_dimensions() does not cover the "
                f"SINK_PAR-materialized params {missing}"))
    return out


def check_g5_dynamic(component_types: dict, model) -> List[Violation]:
    """Every component class that implements hybrid-Jacobian claims
    must be exercised by the SINK_PAR sweep: present in the model and
    actually claiming at least one free parameter there (CLAUDE.md:
    claims 'must appear in test_all_components.py's SINK_PAR')."""
    out = []
    free = set(model.free_params)
    for name, cls in sorted(component_types.items()):
        if "linear_design_names" not in cls.__dict__:
            continue
        comp = model.components.get(name)
        if comp is None:
            out.append(Violation(
                "G5", _class_path(cls), 0,
                f"{name} implements linear_design_names but is not in "
                f"test_all_components.py's SINK_PAR — its claims are "
                f"never swept against the production fit step"))
            continue
        claims = set(comp.linear_design_names())
        if not claims:
            out.append(Violation(
                "G5", _class_path(cls), 0,
                f"{name} is in SINK_PAR but claims no free parameter "
                f"there — free one of its claimable params so the "
                f"sweep exercises the closed-form column"))
        elif not claims <= free:
            out.append(Violation(
                "G5", _class_path(cls), 0,
                f"{name} claims {sorted(claims - free)} which are not "
                f"free in the SINK model (claims must be free "
                f"params)"))
    return out


def _class_path(cls) -> str:
    mod = sys.modules.get(cls.__module__)
    f = getattr(mod, "__file__", None) or cls.__module__
    for marker in ("pint_tpu/", "tools/"):
        i = f.replace(os.sep, "/").rfind(marker)
        if i >= 0:
            return f.replace(os.sep, "/")[i:]
    return f


# --------------------------------------------------------------------
# suppression: pragmas + the committed allowlist
# --------------------------------------------------------------------

def apply_suppressions(report: LintReport, allowlist: List[dict],
                       sources: Dict[str, str]):
    """Drop violations covered by an inline pragma or an allowlist
    entry. An entry suppresses at most ``max_hits`` (default 1)
    violations — a NEW violation that happens to share the substring
    must surface for its own review, not ride an old justification.
    Stale entries (zero hits) become violations themselves."""
    hits = [0] * len(allowlist)
    kept: List[Violation] = []
    for v in report.violations:
        line = ""
        src = sources.get(v.path)
        if src is not None and v.line:
            lines = src.splitlines()
            if v.line <= len(lines):
                line = lines[v.line - 1]
        pragma = PRAGMA_RE.search(line)
        if pragma and pragma.group(1) == v.rule:
            report.suppressed.append((v, f"pragma: {pragma.group(2)}"))
            continue
        hit = None
        for i, e in enumerate(allowlist):
            if e["rule"] != v.rule or e["file"] != v.path:
                continue
            if hits[i] >= e.get("max_hits", 1):
                continue
            if e.get("match") and e["match"] not in (line or v.snippet
                                                     or v.msg):
                if e["match"] not in v.msg:
                    continue
            hits[i] += 1
            hit = e
            break
        if hit is not None:
            report.suppressed.append((v, f"allowlist: {hit['why']}"))
        else:
            kept.append(v)
    report.violations = kept
    for i, e in enumerate(allowlist):
        if not hits[i]:
            report.violations.append(Violation(
                "ALLOWLIST", e["file"], 0,
                f"stale allowlist entry (rule {e['rule']}, match "
                f"{e.get('match')!r}) no longer suppresses anything — "
                f"delete it so the list stays honest", scope="repo"))


# --------------------------------------------------------------------
# driver
# --------------------------------------------------------------------

def run_lint(root: str, dynamic: bool = True,
             use_allowlist: bool = True) -> LintReport:
    report = LintReport()
    modules: List[ModuleInfo] = []
    shell: List[Tuple[str, str]] = []
    sources: Dict[str, str] = {}
    for abspath, relpath in iter_lint_files(root):
        src = open(abspath, encoding="utf-8").read()
        sources[relpath] = src
        report.files_scanned += 1
        if relpath.endswith(".sh"):
            shell.append((relpath, src))
            continue
        try:
            modules.append(ModuleInfo(relpath, src))
        except SyntaxError as e:
            report.violations.append(Violation(
                "PARSE", relpath, e.lineno or 0, f"syntax error: {e}"))
    seed_names = collect_jit_seed_names(modules)
    prod_per_module, prod_private = collect_jit_products(modules)
    # the concurrency rule family (G16/G17) lives in
    # analysis/concurrency; imported lazily like graftflow so AST
    # fixtures in tests can drive the halves standalone
    from pint_tpu.analysis import concurrency as _conc

    g16_hits: Dict[int, int] = {}
    for m in modules:
        mark_jit_regions(m, seed_names.get(m.relpath, set()))
        report.violations += check_g1(m)
        report.violations += check_g2(m)
        report.violations += check_g6_python(m)
        report.violations += check_g6_dispatch(
            m, prod_per_module.get(m.relpath, set()) | prod_private)
        report.violations += check_g12(m)
        report.violations += check_g13(m)
        report.violations += check_g14(m)
        report.violations += check_g15(m)
        report.violations += check_g7(m)
        report.violations += check_g8(m)
        report.violations += _conc.check_g16(m, g16_hits)
        report.violations += _conc.check_g17(m)
    report.violations += _conc.g16_stale_entries(g16_hits)
    report.violations += _conc.check_g16_scrape_paths(modules)
    for relpath, src in shell:
        report.violations += check_g6_shell(relpath, src)
    graph = ClassGraph(modules)
    report.violations += check_g3(graph)
    report.violations += check_g4_static(graph)
    report.violations += check_g5_static(graph)
    # the dataflow rule families (G9/G10) live in analysis/graftflow;
    # imported lazily so the AST fixtures in tests can drive the
    # per-rule halves without the registry machinery
    from pint_tpu.analysis import graftflow

    flow_violations, flow_suppressed = graftflow.run_flow_checks(
        modules)
    report.violations += flow_violations
    if dynamic:
        for v in dynamic_registry_checks(root):
            v.scope = "repo"
            report.violations.append(v)
    allow = []
    if use_allowlist:
        from pint_tpu.analysis.allowlist import ALLOWLIST

        allow = ALLOWLIST
    apply_suppressions(report, allow, sources)
    # registry-sanctioned demotion sites are reviewed suppressions,
    # same standing as allowlist hits — recorded after the allowlist
    # pass (they never were candidate violations)
    report.suppressed.extend(flow_suppressed)
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return report


def changed_file_set(root: str) -> Set[str]:
    """Repo-relative paths changed vs HEAD (staged + unstaged +
    untracked) — the --changed-only scope. Bounded subprocesses (a
    repo on a wedged network mount must not hang the linter)."""
    import subprocess

    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others",
                  "--exclude-standard"]):
        try:
            r = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except Exception:
            continue
        if r.returncode == 0:
            out.update(p.strip() for p in r.stdout.splitlines()
                       if p.strip())
    return out


def find_repo_root(start: Optional[str] = None) -> str:
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(cur, "pint_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            raise SystemExit(
                "graftlint: no pint_tpu/ package found above cwd "
                "(pass --root)")
        cur = parent


def github_annotation(v: Violation) -> str:
    """One GitHub Actions ``::error`` workflow-command line for a
    violation (%/CR/LF escaped per the workflow-command spec;
    repo-scope findings pin to line 1 so the annotation renders)."""
    msg = f"{v.rule}: {v.msg}".replace(
        "%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    return (f"::error file={v.path},line={max(1, v.line)},"
            f"title=graftlint {v.rule}::{msg}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pint_tpu.analysis.graftlint",
        description="project invariant linter (rules G1-G17)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: walk up to pint_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="single-document machine-readable output")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text",
                    help="json: one {file,line,rule,msg} record per "
                         "line (JSONL) plus a trailing summary "
                         "record — the pre-commit/CI wire format; "
                         "github: `::error file=..,line=..::..` "
                         "workflow-annotation lines so CI findings "
                         "land inline on the PR diff")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs "
                         "HEAD (git diff + untracked) — the fast "
                         "pre-commit mode; repo-global findings "
                         "(stale allowlist/registry entries, "
                         "dynamic zoo checks) are skipped unless "
                         "their file changed. The full run remains "
                         "the gate")
    ap.add_argument("--no-dynamic", action="store_true",
                    help="skip the import-the-zoo half of G4/G5")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="report suppressed findings too")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rid, desc in RULES.items():
            print(f"{rid}  {desc}")
        return 0
    root = args.root or find_repo_root(os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    changed = None
    if args.changed_only:
        changed = changed_file_set(root)
        scanned = {rel for _, rel in iter_lint_files(root)}
        # the dynamic zoo half is repo-global and slow; in the fast
        # pre-commit mode run it only when model/test structure moved
        zoo_trigger = any(c.startswith("pint_tpu/models/") or
                          c.startswith("tests/") for c in changed)
        if not (changed & scanned) and not zoo_trigger:
            if args.format == "json":
                print(json.dumps({"summary": True, "clean": True,
                                  "files_scanned": 0, "violations": 0,
                                  "changed_only": True}))
            elif args.format == "github":
                pass  # clean run = zero annotation lines
            else:
                print("graftlint: no lintable files changed")
            return 0
        if args.no_dynamic is False and not zoo_trigger:
            args.no_dynamic = True
    report = run_lint(root, dynamic=not args.no_dynamic,
                      use_allowlist=not args.no_allowlist)
    if changed is not None:
        # repo-scope findings (stale allowlist/registry entries, the
        # dynamic zoo checks, compile-key drift) survive the filter:
        # they are facts about the tree, not about unchanged files
        report.violations = [v for v in report.violations
                             if v.path in changed or
                             v.scope == "repo"]
    if args.format == "github":
        # GitHub Actions workflow-annotation wire format: one
        # ::error line per finding (newlines %0A-escaped per the
        # workflow-command spec) so violations annotate the PR diff
        for v in report.violations:
            print(github_annotation(v))
        return 0 if report.clean else 1
    if args.format == "json":
        for v in report.violations:
            print(json.dumps({"file": v.path, "line": v.line,
                              "rule": v.rule, "msg": v.msg}))
        print(json.dumps({"summary": True, "clean": report.clean,
                          "files_scanned": report.files_scanned,
                          "violations": len(report.violations),
                          "suppressed": len(report.suppressed),
                          "changed_only": bool(args.changed_only)}))
        return 0 if report.clean else 1
    if args.json:
        print(json.dumps({
            "clean": report.clean,
            "files_scanned": report.files_scanned,
            "violations": [v.__dict__ for v in report.violations],
            "suppressed": [
                {**v.__dict__, "reason": why}
                for v, why in report.suppressed],
        }, indent=2))
    else:
        for v in report.violations:
            print(v.format())
        print(f"graftlint: {report.files_scanned} files, "
              f"{len(report.violations)} violation(s), "
              f"{len(report.suppressed)} suppressed")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
