"""pint_tpu.analysis — invariant enforcement for the framework.

Two halves (ISSUE 3 / ARCHITECTURE.md "Static analysis"):

- ``graftlint``: the AST/registry linter encoding the CLAUDE.md
  conventions as rules G1-G8 (``python -m
  pint_tpu.analysis.graftlint``);
- ``sanitizer``: the runtime ``Sanitizer`` context manager that counts
  jit rebuilds per TimingModel (the "params_only must not drop the
  jit" invariant), flags host-array operands crossing into watched
  dispatches, and optionally NaN-checks outputs.
"""

from pint_tpu.analysis.sanitizer import Sanitizer  # noqa: F401

__all__ = ["Sanitizer"]
