"""pint_tpu.analysis — invariant enforcement for the framework.

Three layers (ISSUE 3 + ISSUE 6 / ARCHITECTURE.md "Static analysis"):

- ``graftlint``: the AST/registry linter encoding the CLAUDE.md
  conventions as rules G1-G10 (``python -m
  pint_tpu.analysis.graftlint``);
- ``graftflow`` (+ ``cfg``, ``precision_registry``): the dataflow
  half — dtype-provenance (G9: demotions only at registered
  precision boundaries, no f32 into the dd chain) and trace-constant
  analysis (G10: parameter values are runtime args, cross-checked
  against TimingModel._compile_key), with runtime differential
  validation of its dtype predictions;
- ``sanitizer``: the runtime ``Sanitizer`` context manager that
  counts jit rebuilds per TimingModel (the "params_only must not
  drop the jit" invariant), flags host-array operands crossing into
  watched dispatches (nested pytrees and opaque request objects
  included), NaN-checks outputs, and carries the dtype-probe mode
  that closes the differential loop.
"""

from pint_tpu.analysis.sanitizer import Sanitizer  # noqa: F401

__all__ = ["Sanitizer", "lint_state", "lint_state_safe"]


def lint_state(root=None) -> dict:
    """Analyzer-state block for perf artifacts (bench.py /
    bench_serve.py): a degraded-analysis state — violations in the
    tree, a bloated suppression surface — is labeled in the artifact
    itself, exactly like degraded dispatch already is
    (dispatch_supervisor counters). Static rules only: the dynamic
    zoo half belongs to the test gate, and here it would double the
    artifact's cost for no labeling value."""
    from pint_tpu.analysis import graftlint
    from pint_tpu.analysis.allowlist import ALLOWLIST
    from pint_tpu.analysis.precision_registry import DEMOTIONS, PROBES

    if root is None:
        root = graftlint.find_repo_root(__file__)
    report = graftlint.run_lint(root, dynamic=False)
    # ALLOWLIST-stale findings can be artifacts of skipping the
    # dynamic half (an entry only the zoo checks hit); the lint GATE
    # judges staleness, the artifact label judges the code
    real = [v for v in report.violations if v.rule != "ALLOWLIST"]
    return {
        "clean": not real,
        "violations": len(real),
        "suppressed": len(report.suppressed),
        "allowlist_entries": len(ALLOWLIST),
        "precision_registry_entries": len(DEMOTIONS),
        "dtype_probes": len(PROBES),
        "static_only": True,
    }


def lint_state_safe() -> dict:
    """lint_state that never raises — the ONE wrapper every artifact
    embedder (bench.py, bench_serve.py) shares, so the degraded-
    label shape cannot drift between drivers: a broken analyzer
    yields {"clean": None, "error": ...} instead of killing the
    benchmark record."""
    try:
        return lint_state()
    except Exception as e:
        return {"clean": None, "error": repr(e)}
