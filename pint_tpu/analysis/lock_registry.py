"""Declared lock discipline — the registry graftlint G16 checks the
serve/dispatch/runtime/obs layers against (ISSUE 18).

Policy (ARCHITECTURE.md "Static analysis"): the serve stack's
concurrency contracts — "MetricsServer never takes an engine lock",
"no dispatch under the engine lock", "journal fsync outside the cv"
— were each asserted by one hand-written test. This registry makes
them DECLARED state, the precision_registry.py pattern: every entry
carries a written justification, stale entries fail the lint run, and
graftlint G16 statically enforces three properties:

1. **guarded-field writes** (``GUARDED``): a registered field may be
   written only in ``__init__``, in a ``*_locked``-suffixed method
   (the repo's caller-holds-the-lock naming convention), in one of
   the entry's declared ``holders`` methods, or lexically inside
   ``with self.<lock>``. Anything else is an unsynchronized write to
   state another thread reads under the lock.
2. **scrape-path isolation** (``SCRAPE_ROOTS``): the functions listed
   here must be statically unreachable from any acquisition of a
   registry-listed engine lock — the repo-wide proof behind
   tests/test_metrics.py's "scrape never blocks on the engine lock".
3. **no blocking ops under an engine lock** (``ENGINE_LOCKS`` +
   ``BLOCKING_CALLS``): no supervised dispatch, journal fsync/admit,
   or host solve may run lexically inside a ``with`` on a listed
   engine lock. The scheduler's ``_dispatch_lock`` is deliberately
   NOT listed: it is the dispatch serializer — sealed units issue
   and collect while holding it BY DESIGN, with ``_cv`` released per
   iteration so admission keeps flowing.

The dynamic half (``runtime.locks`` TracedLock, $PINT_TPU_LOCK_TRACE)
checks the same discipline at runtime: ``engine=True`` lock
constructions must agree with ``ENGINE_LOCKS`` here.

Entry fields (GUARDED):
  file     repo-relative path
  cls      owning class name
  field    the guarded attribute (``self.<field>`` writes checked)
  lock     the owning lock attribute; writes must sit inside
           ``with self.<lock>`` (aliases: a Condition built over the
           lock counts — declare it via ``aliases``)
  aliases  additional attribute names whose ``with`` also proves the
           lock held (e.g. ``_cv`` wraps ``_lock``)
  holders  methods allowed to write OUTSIDE a lexical ``with``
           because their ONLY callers hold the lock (each must be
           justified in ``why``)
  why      mandatory justification

A GUARDED entry that matches no write anywhere is stale and fails
the run — the registry cannot rot into a blanket waiver.
"""

# ---------------------------------------------------------------- G16.1
GUARDED = [
    # ------------------------------------------ serve scheduler queue
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_open", lock="_lock", aliases=("_cv",), holders=(),
         why="open-bucket table: submit inserts, the seal/expiry "
             "sweeps and _shed_remaining clear — all under the cv "
             "(or in *_locked helpers whose callers hold it); the "
             "drain loop re-acquires the cv per iteration to pop."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_ready", lock="_lock", aliases=("_cv",), holders=(),
         why="sealed-unit deque between submit (seal under cv) and "
             "the drain loop (popleft under cv, per iteration)."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_nqueued", lock="_lock", aliases=("_cv",), holders=(),
         why="queue depth: capacity checks and the shed policy read "
             "it under the cv; every increment/decrement (admit, "
             "expiry, drain pop, shutdown shed) must hold the cv or "
             "two concurrent submits double-admit past queue_cap."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_earliest_expiry", lock="_lock", aliases=("_cv",),
         holders=(),
         why="amortizes the expiry sweep (skip until due); written "
             "on admit and by _expire_locked, both under the cv."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_drain_stop_at", lock="_lock", aliases=("_cv",),
         holders=("stop",),
         why="shutdown drain bound. stop() writes it BEFORE taking "
             "the cv on purpose: it is a monotonic one-way latch "
             "(None -> a bound, never back) read by the drain loop "
             "under the cv — the benign pre-signal write means a "
             "drain already past the read still gets bounded by the "
             "per-iteration re-read; holding the cv for the write "
             "would add nothing but a stall behind a full sweep."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_dead", lock="_dispatch_lock", holders=(),
         why="kill_restart latch (False -> True, never back): set "
             "by the drain loop while it holds _dispatch_lock; "
             "submit/loop read it opportunistically — a stale read "
             "admits one more request whose future then fails, the "
             "documented crash semantics (journal replay covers it)."),
    dict(file="pint_tpu/serve/scheduler.py", cls="ServeEngine",
         field="_pool_last_collect", lock="_dispatch_lock",
         holders=("_dispatch_finish",),
         why="per-pool last-collect stamp feeding the router's "
             "inter-completion rate sample. Written only in "
             "_dispatch_finish, whose every call site sits inside "
             "_drain_ready's `with self._dispatch_lock:` block — a "
             "holder, not a lexical with (the lock is the caller's)."),
    # --------------------------------------------- admission control
    dict(file="pint_tpu/serve/admission.py", cls="AdmissionController",
         field="_buckets", lock="_lock", holders=(),
         why="tenant -> TokenBucket table: check_quota's get-or-"
             "create + drain + take must be atomic per tenant or a "
             "burst races two buckets into existence."),
    dict(file="pint_tpu/serve/admission.py", cls="AdmissionController",
         field="_shed_times", lock="_lock", holders=(),
         why="burst-detector deque: append + window test + clear "
             "are one atomic decision in note_shed — a torn window "
             "double-fires the shed-burst flight dump."),
    dict(file="pint_tpu/serve/admission.py", cls="AdmissionController",
         field="_tenant_names", lock="_lock",
         holders=("_note_tenant",),
         why="name set behind the derived `tenants` view. "
             "_note_tenant's docstring declares 'caller holds "
             "self._lock' and both call sites (check_quota) do — a "
             "holder by convention, enforced here."),
    # ------------------------------------------------ request journal
    dict(file="pint_tpu/serve/journal.py", cls="RequestJournal",
         field="_fh", lock="_lock", holders=(),
         why="journal file handle: swapped by _compact_locked's "
             "atomic rewrite while _append writes through it — an "
             "unlocked swap loses the record being appended."),
    dict(file="pint_tpu/serve/journal.py", cls="RequestJournal",
         field="_bytes", lock="_lock", holders=(),
         why="running file size driving auto-compaction; updated "
             "per append and reset by the compaction rewrite."),
    dict(file="pint_tpu/serve/journal.py", cls="RequestJournal",
         field="_next_compact", lock="_lock", holders=(),
         why="compaction hysteresis threshold, written only by "
             "_compact_locked (suffix convention) after a rewrite."),
    dict(file="pint_tpu/serve/journal.py", cls="RequestJournal",
         field="_torn_seen", lock="_lock", holders=(),
         why="damaged-record dedup set behind the torn-record "
             "counter (ISSUE 19): written only in __init__ and "
             "_torn_locked (suffix convention — every _scan caller "
             "holds the journal lock); an unlocked add double-counts "
             "a torn line against a concurrent compaction scan."),
    # ------------------------------------------------ serve fleet
    dict(file="pint_tpu/serve/fleet.py", cls="FleetFront",
         field="_state", lock="_lock", holders=(),
         why="worker lifecycle latch (live -> dead -> rehomed): the "
             "sweep's fence + re-home transition and submit's "
             "live-set pick must observe it atomically, or two "
             "sweeps re-home the same dead worker's admits twice "
             "(double-replay = double-serve)."),
    dict(file="pint_tpu/serve/fleet.py", cls="FleetFront",
         field="_rr", lock="_lock", holders=(),
         why="round-robin cursor behind the live-worker pick; torn "
             "increments skew placement, harmless but the lock is "
             "already held for the live-set read."),
    dict(file="pint_tpu/serve/fleet.py", cls="FleetFront",
         field="_inflight", lock="_lock", holders=(),
         why="rid -> original-request map the re-home pass resolves "
             "survivor results into: insert (submit track), pop "
             "(future done callback) and the re-home lookup run on "
             "three different threads."),
]

# ---------------------------------------------------------------- G16.3
# Engine/scheduler locks: admission-critical — every submitter
# serializes on them, so a blocking operation held under one stalls
# the whole deployment's admission path. The dynamic mirror is
# ``engine=True`` in the runtime.locks construction.
ENGINE_LOCKS = [
    dict(file="pint_tpu/serve/scheduler.py",
         attrs=("_lock", "_cv"),
         why="THE engine lock (the cv wraps it): submit, the seal/"
             "expiry sweeps and the serve loop all serialize here. "
             "A supervised dispatch (0.1-0.25 s RTT), a journal "
             "fsync, or a host solve under it turns one slow unit "
             "into a full admission stall — the tail-latency bug "
             "class G16 part 3 + check_dispatch_clear() exist for. "
             "_dispatch_lock is deliberately absent: dispatch under "
             "it IS the design (one drain at a time)."),
]

# Blocking operations banned inside `with <engine lock>` (tail names
# of the call). dispatch/dispatch_async = supervised device dispatch
# (runtime.supervisor); fsync + the journal's admit/ack/progress =
# fsynced disk writes (scheduler.submit journals OUTSIDE the cv on
# purpose); pta_solve_np = the host GLS mirror (seconds at scale).
BLOCKING_CALLS = frozenset({
    "dispatch", "dispatch_async", "fsync",
    "admit", "ack", "progress", "pta_solve_np",
})

# ---------------------------------------------------------------- G16.2
# Scrape-path roots: must be statically unreachable from any
# ENGINE_LOCKS acquisition (BFS over the resolvable call graph —
# same-class self.* calls, same-module calls, imported-module
# attribute calls).
SCRAPE_ROOTS = [
    dict(file="pint_tpu/obs/metrics.py", func="do_GET",
         why="the MetricsServer handler: /metrics renders the "
             "registry (per-metric locks only) and /healthz calls "
             "the health fn — the 'scrape never takes an engine "
             "lock' contract tests/test_metrics.py asserts by "
             "holding eng._lock while scraping."),
    dict(file="pint_tpu/obs/metrics.py", func="default_health",
         why="the /healthz payload builder: breaker snapshots, SLO "
             "watchdog status, numerics verdicts — all process-"
             "global obs state with its own leaf locks."),
    dict(file="pint_tpu/serve/admission.py", func="snapshot",
         why="the admission block of every serve snapshot; "
             "documented lock-free over registry reads (its own "
             "_lock guards only the tenant name set) so a snapshot "
             "never serializes behind the admission hot path."),
]

# Raw threading primitives (G16 sub-check): construction of
# threading.Lock/RLock/Condition in the dispatch/serve/runtime/obs
# layers must go through runtime.locks factories so the traced build
# sees every lock. Sanctioned raw sites carry a G16 pragma with a
# written justification (runtime/locks.py's own internals).


def entry_count() -> int:
    """Registry size (the lint CLI smoke test asserts it is > 0 and
    tests pin drift, the precision_registry pattern)."""
    return len(GUARDED) + len(ENGINE_LOCKS) + len(SCRAPE_ROOTS)
