"""Ensemble MCMC sampler.

Reference: src/pint/sampler.py (EmceeSampler) — a thin wrapper over the
external emcee package, which does not exist in this stack. This is a
self-contained affine-invariant stretch-move ensemble sampler
(Goodman & Weare 2010, the same algorithm emcee implements), designed
around BATCHED posterior evaluation: each half-ensemble's proposals are
scored in ONE vectorized call (BayesianTiming.lnposterior_batch runs
them as a single vmapped device program), so a 64-walker ensemble costs
two device calls per step rather than 64 python evaluations.

The whole-chain-on-device variant (two dispatches per step collapsed
to one per chain chunk) lives in ``pint_tpu.sampling``; the chain
diagnostics shared by both samplers are the ``ChainStats`` mixin
below.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

__all__ = ["EnsembleSampler", "ChainStats"]


class ChainStats:
    """Chain bookkeeping + convergence diagnostics shared by the
    host ``EnsembleSampler`` and the device
    ``sampling.DeviceEnsembleSampler`` (emcee-compatible surface:
    ``chain``/``lnprob``/``get_chain``/``get_autocorr_time``/
    ``converged``)."""

    chain: Optional[np.ndarray] = None    # (nsteps, W, ndim)
    lnprob: Optional[np.ndarray] = None   # (nsteps, W)
    naccepted = 0
    niterations = 0

    @property
    def acceptance_fraction(self) -> float:
        return self.naccepted / max(1, self.niterations)

    def get_chain(self, discard: int = 0, thin: int = 1,
                  flat: bool = False) -> np.ndarray:
        """(nsteps, W, ndim) chain view (emcee-compatible API)."""
        if self.chain is None:
            raise ValueError("run_mcmc first")
        c = self.chain[discard::thin]
        return c.reshape(-1, self.ndim) if flat else c

    def get_autocorr_time(self, c: float = 5.0) -> np.ndarray:
        """Integrated autocorrelation time per parameter, estimated
        from the walker-averaged chain with Sokal's self-consistent
        window M >= c*tau (the estimator emcee uses; reference:
        event_optimize's convergence reporting)."""
        if self.chain is None:
            raise ValueError("run_mcmc first")
        nsteps = self.chain.shape[0]
        taus = np.empty(self.ndim)
        for d in range(self.ndim):
            # mean over walkers first: GW ensembles are exchangeable
            x = self.chain[:, :, d].mean(axis=1)
            x = x - x.mean()
            # FFT autocorrelation
            n = 1 << (2 * nsteps - 1).bit_length()
            f = np.fft.rfft(x, n=n)
            acf = np.fft.irfft(f * np.conjugate(f), n=n)[:nsteps]
            if acf[0] <= 0:
                taus[d] = np.nan
                continue
            acf = acf / acf[0]
            cumtau = 2.0 * np.cumsum(acf) - 1.0
            window = np.arange(nsteps) >= c * cumtau
            m = np.argmax(window) if window.any() else nsteps - 1
            taus[d] = max(cumtau[m], 1.0)
        return taus

    def converged(self, factor: float = 50.0, tau=None) -> bool:
        """emcee's rule of thumb: the chain is long enough when
        nsteps > factor * max(tau). Pass a precomputed ``tau`` to
        avoid re-running the FFT autocorrelation."""
        tau = self.get_autocorr_time() if tau is None else \
            np.asarray(tau)
        if not np.all(np.isfinite(tau)):
            return False
        return self.chain.shape[0] > factor * float(np.max(tau))


class EnsembleSampler(ChainStats):
    """Affine-invariant ensemble sampler with batched posterior calls.

    ``log_prob_batch`` maps an (S, ndim) array to (S,) log posteriors.
    """

    def __init__(self, nwalkers: int, ndim: int,
                 log_prob_batch: Callable[[np.ndarray], np.ndarray],
                 a: float = 2.0,
                 rng: Optional[np.random.Generator] = None):
        if nwalkers < 2 * ndim or nwalkers % 2:
            raise ValueError(
                "need an even nwalkers >= 2*ndim for ensemble moves")
        self.nwalkers = nwalkers
        self.ndim = ndim
        self.log_prob_batch = log_prob_batch
        self.a = float(a)
        self.rng = rng or np.random.default_rng()
        self.chain: Optional[np.ndarray] = None   # (nsteps, W, ndim)
        self.lnprob: Optional[np.ndarray] = None  # (nsteps, W)
        self.naccepted = 0
        self.niterations = 0

    def _stretch_half(self, pos, lp, move, other):
        """One stretch-move update of walkers ``move`` against the
        complementary set ``other``; returns accepted count."""
        n = len(move)
        # z ~ g(z) prop. 1/sqrt(z) on [1/a, a]
        z = ((self.a - 1.0) * self.rng.uniform(size=n) + 1.0) ** 2 \
            / self.a
        partners = other[self.rng.integers(0, len(other), size=n)]
        prop = pos[partners] + z[:, None] * (pos[move] - pos[partners])
        # np.array (OWNED copy, not np.asarray): log_prob_batch may
        # hand back a zero-copy view of a jax device buffer, and with
        # buffer donation enabled that memory can be reused by the
        # NEXT dispatch while these values are still referenced — the
        # runtime counterpart of graftlint G11, copy at the boundary
        lp_prop = np.array(self.log_prob_batch(prop),
                           dtype=np.float64)
        logq = (self.ndim - 1.0) * np.log(z) + lp_prop - lp[move]
        accept = np.log(self.rng.uniform(size=n)) < logq
        pos[move[accept]] = prop[accept]
        lp[move[accept]] = lp_prop[accept]
        return int(accept.sum())

    def run_mcmc(self, p0: np.ndarray, nsteps: int,
                 progress: bool = False) -> np.ndarray:
        """Run the ensemble; returns the final (W, ndim) positions and
        stores the full chain in ``self.chain``."""
        pos = np.array(p0, dtype=np.float64)
        if pos.shape != (self.nwalkers, self.ndim):
            raise ValueError(f"p0 must be {(self.nwalkers, self.ndim)}")
        # np.array (copy): log_prob_batch may hand back a read-only
        # view of a jax device buffer
        lp = np.array(self.log_prob_batch(pos), dtype=np.float64)
        if not np.any(np.isfinite(lp)):
            raise ValueError("no walker starts at finite posterior")
        chain = np.empty((nsteps, self.nwalkers, self.ndim))
        lnprob = np.empty((nsteps, self.nwalkers))
        half = self.nwalkers // 2
        first = np.arange(half)
        second = np.arange(half, self.nwalkers)
        for step in range(nsteps):
            self.naccepted += self._stretch_half(pos, lp, first, second)
            self.naccepted += self._stretch_half(pos, lp, second, first)
            self.niterations += self.nwalkers
            chain[step] = pos
            lnprob[step] = lp
            if progress and (step + 1) % max(1, nsteps // 10) == 0:
                print(f"  step {step + 1}/{nsteps} "
                      f"acc={self.acceptance_fraction:.2f}")
        self.chain = chain
        self.lnprob = lnprob
        return pos
