"""Wideband fitting: joint [TOA; DM] GLS.

Reference: src/pint/fitter.py (WidebandTOAFitter,
WidebandDownhillFitter) + src/pint/pint_matrix.py
(combine_design_matrices_by_quantity). Wideband TOAs carry a per-TOA DM
measurement (-pp_dm/-pp_dme flags); the fit minimizes the stacked
residual

    [ r_time ]   [ M_time  ]
    [ r_dm   ] - [ M_dm    ] dtheta   over  diag([s_toa^2; s_dm^2])

where M_time is the usual phase design matrix (d resid/d theta) and
M_dm = -d DM_model/d theta (r_dm = measured - model). Correlated-noise
bases act on the TOA rows, and bases whose process IS a DM
perturbation (PLDMNoise) additionally couple into the DM rows via
TimingModel.noise_model_dm_designmatrix — the joint GP sees the same
coefficient through both channels, matching the reference's wideband
coupling. Both blocks and the solve reuse the GLS kernel unchanged:
the stack is just a taller whitened least-squares problem.
"""

from __future__ import annotations

import time
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitter import Fitter, MaxiterReached
from pint_tpu.gls import (
    _gls_host_failover_solve,
    _gls_kernel,
    _gls_kernel_svd,
)
from pint_tpu.residuals import Residuals
from pint_tpu.runtime import DispatchError, get_supervisor
from pint_tpu.wideband import DMResiduals, get_wideband_dm

__all__ = ["WidebandTOAFitter", "WidebandDownhillFitter"]


def build_dm_designmatrix(model, toas, names: List[str]) -> np.ndarray:
    """(N, p) matrix d DM_model/d theta_j for the free params in
    ``names`` (column order matched; 'Offset' column = 0: the phase
    offset does not move the DM channel). jacfwd of the SAME traced dm
    function the DM residuals use (TimingModel.build_dm_fn), so the
    design matrix can never desynchronize from the residuals."""
    dm_fn, (free, th) = model.build_dm_fn(toas)
    jac = np.asarray(jax.jacfwd(dm_fn)(jnp.asarray(th)))  # (N, p_free)
    out = np.zeros((toas.ntoas, len(names)))
    for j, nm in enumerate(names):
        if nm == "Offset":
            continue
        out[:, j] = jac[:, free.index(nm)]
    return out


class WidebandTOAFitter(Fitter):
    """Joint TOA+DM GLS fit (reference: WidebandTOAFitter)."""

    def __init__(self, toas, model, residuals=None, track_mode=None):
        get_wideband_dm(toas)  # validate flags up front
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.dm_resids = DMResiduals(toas, model)
        self.noise_resids = None

    def _solve_once(self, threshold=None):
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        self.dm_resids = DMResiduals(self.toas, self.model)
        n = self.toas.ntoas
        M_t, names, _ = self.get_designmatrix()
        M_dm = -build_dm_designmatrix(self.model, self.toas, names)
        M = np.concatenate([np.asarray(M_t), M_dm], axis=0)
        r = np.concatenate([np.asarray(self.resids.time_resids),
                            self.dm_resids.resids])
        nvec = np.concatenate([
            self.model.scaled_toa_uncertainty(self.toas) ** 2,
            self.dm_resids.dm_errors ** 2])
        F_t = self.model.noise_model_designmatrix(self.toas)
        phi = self.model.noise_model_basis_weight(self.toas)
        if F_t is None:
            F = np.zeros((2 * n, 0))
            phi = np.ones(0)
        else:
            # DM-process bases (PLDMNoise) couple into the DM rows
            F_dm = self.model.noise_model_dm_designmatrix(self.toas)
            F = np.concatenate([F_t, F_dm], axis=0)
        try:
            x, cov, chi2, noise = self._solve_stacked_device(
                M, F, phi, r, nvec, threshold)
        except DispatchError as e:
            # host failover: the numpy mirror on the same stacked
            # [time; DM] system — degraded in speed, not correctness
            # (mode-aware: eigh mirror for threshold/degenerate)
            get_supervisor().note_failover("wideband.solve", e)
            x, cov, chi2, noise = _gls_host_failover_solve(
                M, F, phi, r, nvec, threshold=threshold,
                what="wideband normal matrix")
        return (-np.asarray(x), np.asarray(cov), float(chi2),
                np.asarray(noise)[:n], names)

    def _solve_stacked_device(self, M, F, phi, r, nvec, threshold):
        sup = get_supervisor()
        pinned = self._solve_pinned()

        def place():
            # asarray inside the dispatched closure AND the scope:
            # placement follows the pinned device, and H2D to a
            # wedged tunnel hangs like a dispatch — it must ride the
            # watchdog (see GLSFitter._solve_once_device)
            return (jnp.asarray(M), jnp.asarray(F), jnp.asarray(phi),
                    jnp.asarray(r), jnp.asarray(nvec))

        def run_svd(th=None):
            with self._solve_scope():
                if th is None:
                    return _gls_kernel_svd(*place())  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                return _gls_kernel_svd(*place(), threshold=th)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        from pint_tpu import config as _config

        health_on = _config.health_enabled()

        def run_chol(f32mm=False):
            with self._solve_scope():
                return _gls_kernel(*place(), f32mm=f32mm, health=health_on)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        from pint_tpu import obs
        from pint_tpu.obs import health as _health

        with obs.span("wideband.solve_once",
                      fitter=type(self).__name__):
            if threshold is not None:
                x, cov, chi2, noise, _ = sup.dispatch(
                    run_svd, kw={"th": float(threshold)},
                    key="wideband.svd", pinned=pinned)
            else:
                from pint_tpu.parallel.fit_step import _use_f32_matmul

                f32mm = False if pinned else _use_f32_matmul(None)
                out = sup.dispatch(
                    run_chol, kw={"f32mm": f32mm},
                    key="wideband.solve", pinned=pinned)
                x, cov, chi2, noise, _, ok = out[:6]
                # observed AFTER the degenerate-retry decision below
                # (a handled SVD fallback is not an incident); the
                # hv only describes the chol attempt, so it rides
                # only when that result is kept
                hsig = {"values": [x, chi2]}
                if bool(ok) and health_on and len(out) > 6:
                    hsig["hv"] = out[6]
                if not bool(ok):
                    from pint_tpu.fitter import warn_degenerate

                    warn_degenerate("wideband normal matrix")
                    x, cov, chi2, noise, _ = sup.dispatch(
                        run_svd, key="wideband.svd", pinned=pinned)
                    hsig = {"values": [x, chi2]}
                _health.observe("wideband.solve", hsig,
                                key="wideband.solve",
                                pool="host" if pinned else "device")
        return x, cov, chi2, noise

    def fit_toas(self, maxiter=1, threshold=None):
        t0 = time.perf_counter()
        for _ in range(max(1, maxiter)):
            x, cov, chi2, noise, names = self._solve_once(threshold)
            self.update_model(x, names)
        x, cov, chi2, noise, names = self._solve_once(threshold)
        self.set_uncertainties(cov, names)
        self.noise_resids = noise
        self.converged = True
        self._record_stats(chi2, max(1, maxiter), t0,
                           dof=self._wb_dof())
        return chi2

    @property
    def chi2_dm(self) -> float:
        return self.dm_resids.chi2

    def _wb_dof(self) -> int:
        """chi2 sums over 2N stacked TOA+DM measurements."""
        return 2 * self.toas.ntoas - len(self.model.free_params) - 1


class WidebandDownhillFitter(WidebandTOAFitter):
    """Step-halving downhill wrapper over the wideband step (reference:
    WidebandDownhillFitter)."""

    def _chi2_here(self) -> float:
        from pint_tpu.gls import gls_chi2

        r = Residuals(self.toas, self.model,
                      track_mode=self.track_mode).time_resids
        return gls_chi2(self.model, self.toas, resids=r) + \
            DMResiduals(self.toas, self.model).chi2

    def fit_toas(self, maxiter=20, threshold=None, min_lambda=1e-3,
                 required_chi2_decrease=1e-2):
        t0 = time.perf_counter()
        iterations = 0
        best_chi2 = self._chi2_here()
        x = cov = noise = names = None
        converged = False
        for _ in range(maxiter):
            iterations += 1
            x, cov, _, noise, names = self._solve_once(threshold)
            lam, accepted = 1.0, False
            while lam >= min_lambda:
                self.update_model(lam * x, names)
                new_chi2 = self._chi2_here()
                if new_chi2 <= best_chi2 + 1e-12:
                    accepted = True
                    break
                self.update_model(-lam * x, names)
                lam /= 2.0
            if not accepted:
                converged = True
                break
            improved = best_chi2 - new_chi2
            best_chi2 = new_chi2
            if improved < required_chi2_decrease:
                converged = True
                break
        else:
            raise MaxiterReached(
                f"no convergence in {maxiter} wideband iterations")
        self.converged = converged
        x, cov, _, noise, names = self._solve_once(threshold)
        self.set_uncertainties(cov, names)
        self.noise_resids = noise
        self._record_stats(best_chi2, iterations, t0,
                           dof=self._wb_dof())
        return best_chi2
