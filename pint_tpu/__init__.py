"""pint_tpu — a TPU-native pulsar-timing framework.

A ground-up re-design of the capabilities of clp3ef/PINT (a fork of
nanograv/PINT, ``src/pint/``) for JAX/XLA on TPU:

- time and phase are carried in double-double (two-float64) arithmetic
  (``pint_tpu.ops.dd``) instead of x87 ``np.longdouble``
  (reference: src/pint/pulsar_mjd.py, src/pint/phase.py);
- the per-TOA delay/phase component stack is a registry of pure jittable
  functions over a flat ``ToaBatch`` struct-of-arrays pytree
  (reference: src/pint/models/timing_model.py TimingModel.delay/phase);
- design matrices come from ``jax.jacfwd`` over the flat parameter vector
  (reference: TimingModel.designmatrix / d_phase_d_param dispatch);
- the GLS noise-covariance Woodbury solve is one jit-compiled XLA kernel
  (reference: src/pint/fitter.py GLSFitter.fit_toas);
- a second batch axis vmaps/shards independent pulsars over a TPU mesh
  (PTA-scale fits).

Host Python does parsing, registries and orchestration; device code is a
closed set of pure functions. Everything numerical runs in float64
(``jax_enable_x64``), with double-double pairs where ~1 ns over decades is
required.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

# Physical constants (SI unless noted). Values per SURVEY.md Appendix A.1.
c_m_s = 299_792_458.0  # speed of light, exact
AU_m = 1.495_978_707_00e11  # astronomical unit, IAU 2012 exact
pc_m = 3.085_677_581_49e16  # parsec
Tsun_s = 4.925_490_947e-6  # GM_sun/c^3 [s] — solar Shapiro scale
GMsun_m3_s2 = 1.327_124_400_18e20

# Dispersion constant, TEMPO convention (exact 1/2.41e-4), NOT the physical
# 4148.808 value — kept for .par compatibility
# (reference: src/pint/__init__.py DMconst).
DMconst = 1.0 / 2.41e-4  # s MHz^2 pc^-1 cm^3

SECS_PER_DAY = 86400.0
MJD_J2000 = 51544.5  # TT epoch J2000.0 as MJD
light_second_m = c_m_s  # 1 lt-s in meters

def __getattr__(name):
    # Lazy top-level API (avoids import cycles during bring-up):
    # pint_tpu.get_model / get_model_and_toas / get_TOAs mirror the
    # reference's pint.get_model etc. (src/pint/models/model_builder.py).
    try:
        if name in ("get_model", "get_model_and_toas"):
            from pint_tpu.models import model_builder

            return getattr(model_builder, name)
        if name == "get_TOAs":
            from pint_tpu import toa

            return toa.get_TOAs
    except ModuleNotFoundError as e:
        raise AttributeError(
            f"pint_tpu.{name} is not available yet: {e}"
        ) from e
    raise AttributeError(f"module 'pint_tpu' has no attribute {name!r}")
