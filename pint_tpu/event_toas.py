"""Photon-event ingestion: mission FITS event tables -> TOAs.

Reference: src/pint/event_toas.py (load_fits_TOAs, load_event_TOAs,
per-mission wrappers) and src/pint/fermi_toas.py (load_Fermi_TOAs,
photon weights). Events carry no TOA uncertainty; phases are assigned
by evaluating the timing model at the photon times.

Mission time scales: event TIME columns count seconds from the mission
MJDREF (MJDREFI + MJDREFF) in the header's TIMESYS. Barycentered event
files (TIMESYS=TDB, TIMEREF=SOLARSYSTEM) map directly onto '@'
(barycenter) TOAs — the supported fast path. Un-barycentered TT files
need the spacecraft orbit (satellite observatories); loading them
without one raises rather than silently mis-assigning phases.
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import numpy as np

from pint_tpu.io.fits import read_events_fits
from pint_tpu.toa import TOAs, get_TOAs_array

__all__ = ["load_fits_TOAs", "load_event_TOAs", "load_Fermi_TOAs",
           "load_NICER_TOAs", "load_RXTE_TOAs", "load_NuSTAR_TOAs",
           "load_Swift_TOAs", "load_XMM_TOAs", "get_event_weights",
           "get_fits_TOAs", "get_event_TOAs", "get_Fermi_TOAs", "get_NICER_TOAs", "get_RXTE_TOAs", "get_NuSTAR_TOAs", "get_Swift_TOAs", "get_XMM_TOAs"]

# (MJDREFI, MJDREFF) fallbacks when the header omits them
MISSION_MJDREF = {
    "fermi": (51910, 7.428703703703703e-4),
    "nicer": (56658, 7.775925925925926e-4),
    "rxte": (49353, 6.965740740740740e-4),
    "nustar": (55197, 7.660185185185185e-4),
    "swift": (51910, 7.428703703703703e-4),
    "xmm": (50814, 0.0),
}


def _mjdref(header, mission: Optional[str]) -> Tuple[float, float]:
    if "MJDREFI" in header:
        return float(header["MJDREFI"]), float(header.get("MJDREFF", 0.0))
    if "MJDREF" in header:
        v = float(header["MJDREF"])
        return float(np.floor(v)), v - np.floor(v)
    if mission and mission.lower() in MISSION_MJDREF:
        return MISSION_MJDREF[mission.lower()]
    raise ValueError("event file lacks MJDREF and mission is unknown")


def load_fits_TOAs(eventfile, mission: Optional[str] = None,
                   weightcolumn: Optional[str] = None,
                   minmjd: float = -np.inf, maxmjd: float = np.inf,
                   ephem: Optional[str] = None,
                   planets: bool = False,
                   orbit_file=None) -> TOAs:
    """Read a FITS event table into TOAs (reference:
    event_toas.load_fits_TOAs). Photon weights (e.g. Fermi photon
    probabilities) are attached as a per-TOA flag ``-weight``.

    Barycentered files (TIMESYS=TDB) become '@' TOAs directly.
    Un-barycentered TT files need ``orbit_file`` (or a previously
    registered satellite observatory named after ``mission``): photon
    times convert TT->UTC through the leap table and the spacecraft's
    interpolated orbit supplies the observatory position."""
    cols, header = read_events_fits(eventfile)
    timesys = str(header.get("TIMESYS", "TT")).strip().upper()
    obs_name = "barycenter"
    if timesys != "TDB":
        from pint_tpu.observatory import get_observatory
        from pint_tpu.observatory.satellite_obs import (
            get_satellite_observatory,
        )

        if orbit_file is not None:
            if mission is None:
                mission = str(header.get("TELESCOP", "sat")).lower()
            get_satellite_observatory(mission, orbit_file)
            obs_name = mission.lower()
        else:
            try:
                if mission is not None:
                    get_observatory(mission.lower())
                    obs_name = mission.lower()
                else:
                    raise KeyError("no mission")
            except KeyError:
                raise NotImplementedError(
                    f"TIMESYS={timesys}: un-barycentered event files "
                    "need a spacecraft orbit file (orbit_file=...)")
    key = next((k for k in cols if k.upper() == "TIME"), None)
    if key is None:
        raise ValueError("event table has no TIME column")
    mjdrefi, mjdreff = _mjdref(header, mission)
    tsec = np.asarray(cols[key], dtype=np.float64)
    tsec = tsec + float(header.get("TIMEZERO", 0.0))
    # split precisely: day from the integer part of sec/86400 relative
    # to MJDREFI; the fractional seconds stay at full f64 resolution
    day_off = np.floor(tsec / 86400.0)
    frac = (tsec - day_off * 86400.0) / 86400.0 + mjdreff
    day = mjdrefi + day_off
    carry = np.floor(frac)
    day, frac = day + carry, frac - carry
    if obs_name != "barycenter":
        # photon TIME is TT; the TOA pipeline expects UTC
        from pint_tpu.time.scales import tt_mjd_to_utc_mjd

        day, frac = tt_mjd_to_utc_mjd(day, frac)
    mjd_float = day + frac
    keep = (mjd_float >= minmjd) & (mjd_float <= maxmjd)
    day, frac = day[keep], frac[keep]

    flags = [dict() for _ in range(day.size)]
    if weightcolumn is not None:
        wkey = next((k for k in cols if k.upper() ==
                     weightcolumn.upper()), None)
        if wkey is None:
            raise ValueError(f"no weight column {weightcolumn!r}")
        wts = np.asarray(cols[wkey], dtype=np.float64)[keep]
        for f, wval in zip(flags, wts):
            f["weight"] = f"{wval:.8g}"

    from pint_tpu.ops import dd_np

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = get_TOAs_array((day, dd_np.dd(frac)), obs=obs_name,
                           freqs=np.inf, errors=0.0, flags=flags,
                           ephem=ephem, planets=planets)
    t.names = [f"photon{i}" for i in range(t.ntoas)]
    return t


def load_event_TOAs(eventfile, mission: str, **kw) -> TOAs:
    """Mission-dispatching wrapper (reference: load_event_TOAs)."""
    return load_fits_TOAs(eventfile, mission=mission, **kw)


def load_Fermi_TOAs(eventfile, weightcolumn: Optional[str] = None,
                    **kw) -> TOAs:
    """Fermi-LAT FT1 loader; weightcolumn typically 'MODEL_WEIGHT' or a
    column produced by gtsrcprob (reference: fermi_toas.load_Fermi_TOAs)."""
    return load_fits_TOAs(eventfile, mission="fermi",
                          weightcolumn=weightcolumn, **kw)


def load_NICER_TOAs(eventfile, **kw) -> TOAs:
    return load_fits_TOAs(eventfile, mission="nicer", **kw)


def load_RXTE_TOAs(eventfile, **kw) -> TOAs:
    return load_fits_TOAs(eventfile, mission="rxte", **kw)


def load_NuSTAR_TOAs(eventfile, **kw) -> TOAs:
    return load_fits_TOAs(eventfile, mission="nustar", **kw)


def load_Swift_TOAs(eventfile, **kw) -> TOAs:
    return load_fits_TOAs(eventfile, mission="swift", **kw)


def load_XMM_TOAs(eventfile, **kw) -> TOAs:
    return load_fits_TOAs(eventfile, mission="xmm", **kw)


def get_event_weights(toas: TOAs) -> Optional[np.ndarray]:
    """Per-photon weights from the -weight flag, or None if absent."""
    if not any("weight" in f for f in toas.flags):
        return None
    return np.array([float(f.get("weight", 1.0)) for f in toas.flags])


# the reference's modern entry-point names (get_* returning a fully
# computed TOAs object — which is what the load_* functions here
# already produce; reference: event_toas.get_NICER_TOAs etc.)
get_fits_TOAs = load_fits_TOAs
get_event_TOAs = load_event_TOAs
get_Fermi_TOAs = load_Fermi_TOAs
get_NICER_TOAs = load_NICER_TOAs
get_RXTE_TOAs = load_RXTE_TOAs
get_NuSTAR_TOAs = load_NuSTAR_TOAs
get_Swift_TOAs = load_Swift_TOAs
get_XMM_TOAs = load_XMM_TOAs
