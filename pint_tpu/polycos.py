"""Polynomial ephemerides ("polycos") for observatory folding.

Reference: src/pint/polycos.py (Polycos.generate_polycos,
eval_abs_phase, eval_spin_freq, TEMPO polyco file I/O). A polyco block
predicts absolute pulse phase over a short segment as

    phase(T) = RPHASE + 60 F0 DT + C1 + C2 DT + ... + Cn DT^(n-1)

with DT = (T - TMID) in minutes (the TEMPO convention), so a telescope
backend can fold in real time without the full timing chain. The spin
frequency is the DT-derivative / 60.

TPU-first shape of the generator: all segments' Chebyshev sample
epochs are built as ONE TOAs batch and evaluated through one jitted
phase call (the reference loops segments, re-running astropy
machinery per segment); the per-segment least-squares fits are tiny
host solves. Phase samples come back as dd, and the large reference
part RPHASE + 60 F0 DT is removed in exact dd before the f64 fit, so
~1e10-turn absolutes never meet the polynomial algebra.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from pint_tpu.ops import dd_np

__all__ = ["PolycoEntry", "Polycos"]

SECS_PER_DAY = 86400.0
MIN_PER_DAY = 1440.0


@dataclass
class PolycoEntry:
    """One polyco block (reference: polycos table row)."""

    psrname: str
    tmid: float                 # MJD (UTC, pulsar convention)
    rphase_int: float           # integer part of phase at TMID
    rphase_frac: float          # fractional part of phase at TMID
    f0: float                   # reference spin frequency [Hz]
    obs: str
    span_min: float
    coeffs: np.ndarray = field(default_factory=lambda: np.zeros(1))
    obsfreq_mhz: float = np.inf
    dm: float = 0.0

    def dt_min(self, mjds) -> np.ndarray:
        return (np.asarray(mjds, np.float64) - self.tmid) * MIN_PER_DAY

    def covers(self, mjds) -> np.ndarray:
        return np.abs(self.dt_min(mjds)) <= self.span_min / 2.0

    def abs_phase(self, mjds):
        """(int turns, frac turns) at the given MJDs — split so the
        ~1e10-turn absolute never loses the sub-turn part."""
        dt = self.dt_min(mjds)
        poly = np.polynomial.polynomial.polyval(dt, self.coeffs)
        # 60 F0 dt can reach ~1e7 turns over a span: split it
        spin = 60.0 * self.f0 * dt
        spin_i = np.floor(spin)
        frac = self.rphase_frac + (spin - spin_i) + poly
        carry = np.floor(frac)
        return (self.rphase_int + spin_i + carry), (frac - carry)

    def spin_freq(self, mjds) -> np.ndarray:
        """Apparent (topocentric) spin frequency [Hz]."""
        dt = self.dt_min(mjds)
        dcoef = np.polynomial.polynomial.polyder(self.coeffs)
        return self.f0 + np.polynomial.polynomial.polyval(
            dt, dcoef) / 60.0


class Polycos:
    """A set of polyco segments + evaluation and TEMPO-format I/O
    (reference: polycos.Polycos)."""

    def __init__(self, entries: Optional[List[PolycoEntry]] = None):
        self.entries = list(entries or [])

    # ------------------------------------------------- generation

    @classmethod
    def generate_polycos(cls, model, mjd_start: float, mjd_end: float,
                         obs: str, seg_length_min: float = 60.0,
                         ncoeff: int = 12,
                         obsfreq_mhz: float = 1400.0) -> "Polycos":
        """Fit ``ncoeff``-term blocks of ``seg_length_min`` minutes
        covering [mjd_start, mjd_end] for observatory ``obs``
        (reference: Polycos.generate_polycos). All segments' Chebyshev
        nodes are evaluated through ONE phase call."""
        from pint_tpu.toa import get_TOAs_array

        if ncoeff < 2:
            raise ValueError("ncoeff must be >= 2")
        seg_d = seg_length_min / MIN_PER_DAY
        nseg = max(1, int(np.ceil((mjd_end - mjd_start) / seg_d)))
        tmids = mjd_start + (np.arange(nseg) + 0.5) * seg_d
        # Chebyshev nodes per segment (oversampled 2x for a stable LS)
        nnode = max(2 * ncoeff, ncoeff + 4)
        k = (np.arange(nnode) + 0.5) / nnode
        nodes = -np.cos(np.pi * k)          # (-1, 1)
        mjds = (tmids[:, None]
                + nodes[None, :] * seg_d / 2.0).ravel()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            toas = get_TOAs_array(
                mjds, obs=obs, freqs=obsfreq_mhz, errors=1.0,
                ephem=model.EPHEM.value,
                planets=bool(model.PLANET_SHAPIRO.value))
            ph = model.phase(toas, abs_phase=True).turns
        ph = (np.asarray(ph.hi, np.float64),
              np.asarray(ph.lo, np.float64))
        f0 = float(model.F0.value)
        try:
            dm = float(model.get_param("DM").value or 0.0)
        except KeyError:
            dm = 0.0
        psr = str(model.PSR.value or "PSR")
        entries = []
        for s in range(nseg):
            sl = slice(s * nnode, (s + 1) * nnode)
            seg_ph = (ph[0][sl], ph[1][sl])
            dt_min = (mjds[sl] - tmids[s]) * MIN_PER_DAY
            # reference part RPHASE + 60 F0 DT removed in exact dd
            tmid_idx = np.argmin(np.abs(dt_min))
            ref = dd_np.add_f(
                dd_np.mul_f(dd_np.dd(dt_min), 60.0 * f0), 0.0)
            resid = dd_np.sub(seg_ph, ref)
            # RPHASE = phase at TMID: interpolate the residual's int
            # level from the node nearest TMID (the residual varies by
            # << 1 turn per minute there)
            r0 = dd_np.to_f64(
                (resid[0][tmid_idx], resid[1][tmid_idx]))
            rphase_int = np.floor(r0)
            y = dd_np.to_f64(resid) - rphase_int
            # least squares in a scaled variable for conditioning,
            # then map back to monomials in DT
            half_min = seg_length_min / 2.0
            x = dt_min / half_min
            V = np.polynomial.chebyshev.chebvander(x, ncoeff - 1)
            c_cheb, *_ = np.linalg.lstsq(V, y, rcond=None)
            c_x = np.polynomial.chebyshev.cheb2poly(c_cheb)
            scale = half_min ** -np.arange(len(c_x))
            coeffs = c_x * scale
            # the fractional reference phase rides in coeffs[0];
            # rphase_frac stays 0 so there is exactly one home for it
            entries.append(PolycoEntry(
                psrname=psr, tmid=float(tmids[s]),
                rphase_int=float(rphase_int), rphase_frac=0.0,
                f0=f0, obs=obs, span_min=float(seg_length_min),
                coeffs=coeffs, obsfreq_mhz=float(obsfreq_mhz),
                dm=dm))
        return cls(entries)

    # ------------------------------------------------- evaluation

    def _entry_for(self, mjds) -> np.ndarray:
        tmids = np.array([e.tmid for e in self.entries])
        idx = np.argmin(
            np.abs(np.asarray(mjds, np.float64)[:, None]
                   - tmids[None, :]), axis=1)
        return idx

    def eval_abs_phase(self, mjds):
        """(int, frac) absolute phase at each MJD (reference:
        Polycos.eval_abs_phase)."""
        mjds = np.atleast_1d(np.asarray(mjds, np.float64))
        idx = self._entry_for(mjds)
        pi = np.zeros(len(mjds))
        pf = np.zeros(len(mjds))
        for s in np.unique(idx):
            m = idx == s
            a, b = self.entries[s].abs_phase(mjds[m])
            pi[m], pf[m] = a, b
        return pi, pf

    def eval_spin_freq(self, mjds) -> np.ndarray:
        """Apparent spin frequency [Hz] (reference:
        Polycos.eval_spin_freq)."""
        mjds = np.atleast_1d(np.asarray(mjds, np.float64))
        idx = self._entry_for(mjds)
        out = np.zeros(len(mjds))
        for s in np.unique(idx):
            m = idx == s
            out[m] = self.entries[s].spin_freq(mjds[m])
        return out

    # ------------------------------------------------- TEMPO format

    @staticmethod
    def _fmt_d(x: float) -> str:
        """Fortran D-exponent float, TEMPO polyco style."""
        s = f"{x: .17e}"
        return s.replace("e", "D")

    def write_polyco_file(self, path: str):
        """TEMPO polyco.dat layout (reference:
        Polycos.write_polyco_file): header line (name, date, utc,
        tmid, dm), data line (rphase, f0, obs, span, ncoeff,
        obsfreq), then coefficients three per line with D
        exponents."""
        with open(path, "w") as f:
            for e in self.entries:
                rph = e.rphase_int + e.rphase_frac + e.coeffs[0]
                # TMID carries 15 decimals (TEMPO's classic 11 would
                # quantize at ~0.4 us, i.e. ~1e-4 turns at 218 Hz —
                # whitespace-tolerant parsers read either)
                f.write(f"{e.psrname:<10s} {'':9s}{'':7s}"
                        f"{e.tmid:24.15f}{e.dm:21.6f}\n")
                f.write(f"{rph:20.6f}{e.f0:18.12f}"
                        f"{e.obs:>5s}{int(e.span_min):5d}"
                        f"{len(e.coeffs):5d}{e.obsfreq_mhz:10.3f}\n")
                for i in range(0, len(e.coeffs), 3):
                    row = e.coeffs[i:i + 3].copy()
                    if i == 0:
                        row = row.copy()
                        row[0] = 0.0  # folded into RPHASE above
                    f.write("".join(f"{self._fmt_d(c):>25s}"
                                    for c in row) + "\n")

    @classmethod
    def read_polyco_file(cls, path: str) -> "Polycos":
        """Inverse of write_polyco_file."""
        entries = []
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
        i = 0
        while i < len(lines):
            h = lines[i].split()
            psr = h[0]
            tmid = float(h[-2])
            dm = float(h[-1])
            d = lines[i + 1].split()
            rph = float(d[0])
            f0 = float(d[1])
            obs = d[2]
            span = float(d[3])
            nco = int(d[4])
            obsfreq = float(d[5])
            nrows = (nco + 2) // 3
            vals: List[float] = []
            for r in range(nrows):
                for tok in lines[i + 2 + r].split():
                    vals.append(float(tok.replace("D", "e")))
            coeffs = np.asarray(vals[:nco])
            rint = np.floor(rph)
            coeffs = coeffs.copy()
            coeffs[0] = coeffs[0] + (rph - rint)
            entries.append(PolycoEntry(
                psrname=psr, tmid=tmid, rphase_int=float(rint),
                rphase_frac=0.0, f0=f0, obs=obs, span_min=span,
                coeffs=coeffs, obsfreq_mhz=obsfreq, dm=dm))
            i += 2 + nrows
        return cls(entries)
