"""Photon phaseogram plotting (reference: src/pint/plot_utils.py
phaseogram / phaseogram_binned). matplotlib is imported lazily with
the Agg backend so headless use works."""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["phaseogram", "phaseogram_binned", "plot_priors"]


def _mpl():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def phaseogram(mjds, phases, weights=None, bins: int = 64,
               rotate: float = 0.0, title: Optional[str] = None,
               plotfile: Optional[str] = None):
    """2-D photon phaseogram (phase x time, two cycles) over a summed
    pulse profile (reference: plot_utils.phaseogram). Returns the
    matplotlib figure."""
    plt = _mpl()
    mjds = np.asarray(mjds, dtype=np.float64)
    ph = np.mod(np.asarray(phases, dtype=np.float64) + rotate, 1.0)
    w = np.ones_like(ph) if weights is None else np.asarray(weights)
    ph2 = np.concatenate([ph, ph + 1.0])
    mj2 = np.concatenate([mjds, mjds])
    w2 = np.concatenate([w, w])
    fig, (ax0, ax1) = plt.subplots(
        2, 1, sharex=True, figsize=(7, 8),
        gridspec_kw={"height_ratios": [1, 3]})
    prof, edges = np.histogram(ph2, bins=2 * bins, range=(0, 2),
                               weights=w2)
    ax0.step(edges[:-1], prof, where="post")
    ax0.set_ylabel("counts")
    if title:
        ax0.set_title(title)
    tb = max(16, min(64, mjds.size // 50))
    H, xe, ye = np.histogram2d(
        ph2, mj2, bins=[2 * bins, tb],
        range=[[0, 2], [mjds.min(), mjds.max()]], weights=w2)
    ax1.imshow(H.T, origin="lower", aspect="auto",
               extent=[0, 2, mjds.min(), mjds.max()], cmap="Greys")
    ax1.set_xlabel("pulse phase")
    ax1.set_ylabel("MJD")
    if plotfile:
        fig.savefig(plotfile, dpi=100)
        plt.close(fig)
    return fig


def phaseogram_binned(mjds, phases, weights=None, bins: int = 32,
                      **kw):
    """Pre-binned variant (reference: plot_utils.phaseogram_binned) —
    same figure at coarser default binning for sparse data."""
    return phaseogram(mjds, phases, weights=weights, bins=bins, **kw)


def plot_priors(model, chains, burnin: int = 0,
                bins: int = 40, plotfile: Optional[str] = None):
    """Posterior histograms per sampled parameter with the prior pdf
    overplotted (reference: plot_utils.plot_priors)."""
    plt = _mpl()
    names = list(chains.keys()) if isinstance(chains, dict) else None
    if names is None:
        raise ValueError("chains must be {param: samples}")
    n = len(names)
    fig, axes = plt.subplots(n, 1, figsize=(6, 2.2 * n), squeeze=False)
    for ax, nm in zip(axes[:, 0], names):
        samp = np.asarray(chains[nm])[burnin:]
        ax.hist(samp, bins=bins, density=True, alpha=0.6)
        p = model.get_param(nm)
        if getattr(p, "prior", None) is not None:
            xs = np.linspace(samp.min(), samp.max(), 200)
            ax.plot(xs, np.exp(np.asarray(p.prior.logpdf(xs))))
        ax.set_ylabel(nm)
    if plotfile:
        fig.savefig(plotfile, dpi=100)
        plt.close(fig)
    return fig
