"""Runtime data / configuration access.

Reference: src/pint/config.py (runtimefile, datadir, examplefile) +
the env-var override set the reference honors ($PINT_CLOCK_OVERRIDE
etc.; SURVEY.md §5 config row). Here:

- data shipped with the package is embedded in source modules (sites,
  leap seconds, nutation tables) — datadir() points at the package;
- $PINT_TPU_CLOCK_DIR   : directory of TEMPO/TEMPO2 clock files
- $PINT_TPU_EPHEM_DIR   : directory of SPK .bsp ephemeris kernels
- $PINT_TPU_OBS_OVERRIDE: JSON file overriding the observatory table
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Tuple

__all__ = ["datadir", "runtimefile", "clock_dir", "ephem_dir",
           "obs_override", "enable_compile_cache", "solve_device",
           "solve_streaming", "stream_chunk",
           "solve_scope", "dispatch_rtt_ms", "auto_steps_per_dispatch",
           "remeasure_dispatch_rtt", "dispatch_deadline_ms",
           "dispatch_rtt_override_ms",
           "dispatch_retries", "dispatch_backoff_ms",
           "dispatch_compile_allowance_ms", "breaker_threshold",
           "breaker_cooldown_s", "breaker_probe_timeout_s",
           "donation_enabled", "whole_fit_enabled",
           "serve_bucket_edges", "serve_window_s", "serve_max_batch",
           "serve_queue_cap", "serve_pipeline_depth",
           "tenant_qps", "tenant_burst", "shed_policy", "aot_dir",
           "journal_path", "serve_drain_timeout_s",
           "chain_chunk_steps", "gwb_chunk", "journal_compact_bytes",
           "trace_enabled", "trace_stream_path", "trace_ring_size",
           "flight_dir", "f32_mode", "no_pallas", "slo_enabled",
           "slo_interval_s", "slo_specs", "metrics_port",
           "health_enabled", "shadow_rate", "health_drift_sigma",
           "health_chi2_factor", "health_resid_sigma",
           "health_cg_budget_frac", "perf_enabled",
           "compile_ledger_path", "profile_dir", "profile_max_s",
           "lock_trace_enabled", "pool_spec", "fleet_lease_ttl_s",
           "fleet_heartbeat_s", "fleet_workers"]

_RTT_MS: dict = {}
_WARNED_ENV: set = set()


def _env_number(name: str, default, cast=float):
    """Parse a numeric env override, warning (once per distinct bad
    value) instead of silently ignoring a typo — the ADVICE round-5
    failure mode for $PINT_TPU_DISPATCH_RTT_MS."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        if (name, raw) not in _WARNED_ENV:
            _WARNED_ENV.add((name, raw))
            from pint_tpu.logging import log

            log.warning("unparsable $%s=%r; using %r", name, raw,
                        default)
        return default


def dispatch_rtt_override_ms():
    """The validated $PINT_TPU_DISPATCH_RTT_MS override, or None.

    The ONE parser for the override (ISSUE 10 satellite, round-5
    advisor finding): ``dispatch_rtt_ms`` and the supervisor's
    deadline/drift logic both read the env through here, so the
    validation — parse BEFORE any per-backend cache lookup, warn on a
    bad value instead of silently ignoring it — can never diverge
    between the two consumers. Beyond parseability, the value must be
    a finite positive float: a zero/negative/NaN/inf RTT would poison
    every watchdog-deadline prediction and the power-of-two K re-pick
    downstream, so those warn (once per distinct bad value) and are
    ignored like a typo."""
    import math

    val = _env_number("PINT_TPU_DISPATCH_RTT_MS", None)
    if val is None:
        return None
    val = float(val)
    if not math.isfinite(val) or val <= 0.0:
        raw = os.environ.get("PINT_TPU_DISPATCH_RTT_MS")
        key = ("PINT_TPU_DISPATCH_RTT_MS", f"range:{raw}")
        if key not in _WARNED_ENV:
            _WARNED_ENV.add(key)
            from pint_tpu.logging import log

            log.warning("$PINT_TPU_DISPATCH_RTT_MS=%r is not a "
                        "finite positive RTT; ignoring the override",
                        raw)
        return None
    return val


def dispatch_rtt_ms() -> float:
    """Measured round-trip latency of ONE trivial dispatch on the
    default backend (ms), cached per backend per process. This is the
    fixed cost every device program pays regardless of its math:
    ~0.1-0.25 ms on a local chip or CPU, 100-250 ms over the axon TPU
    tunnel (measured round 4). The device fitters size their
    steps-per-dispatch chaining from it instead of a hard-coded 8.
    Override with $PINT_TPU_DISPATCH_RTT_MS (a float) to skip the
    measurement — VALIDATED and read BEFORE the per-backend cache
    (dispatch_rtt_override_ms) so a mid-process override (or a
    changed one) takes effect immediately; an unparsable or
    out-of-range value logs a warning instead of silently falling
    back (ADVICE round 5 / ISSUE 10 satellite)."""
    import time

    import jax
    import jax.numpy as jnp

    env = dispatch_rtt_override_ms()
    if env is not None:
        return env
    backend = jax.default_backend()
    if backend in _RTT_MS:
        return _RTT_MS[backend]
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.asarray(0.0)
    float(f(x))  # compile + first dispatch
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(f(x))  # scalar D2H read: the only sync that can't lie
        ts.append(time.perf_counter() - t0)
    _RTT_MS[backend] = min(ts) * 1e3
    return _RTT_MS[backend]


def auto_steps_per_dispatch() -> int:
    """Downhill iterations to chain per device program, sized from the
    measured dispatch RTT: 1 on the CPU backend (dispatch is ~us and
    the plain step keeps compile time down); on an accelerator, enough
    iterations that the fixed dispatch cost amortizes to <=8 ms per
    iteration (smallest power of two >= rtt/8, clamped to [4, 32] —
    ~4 on a local chip, 16-32 over the 100-250 ms axon tunnel). Quantizing matters:
    K is part of the chained program's compile key, and the tunnel
    RTT is noisy session-to-session — a raw round(rtt/8) would give
    ~28 distinct K values, each a cold (multi-minute, remote) compile;
    powers of two bound it to 4 cache entries. The chained loop
    early-exits on in-kernel convergence (build_fit_loop's
    lax.while_loop), so a generous K costs compile size, not wasted
    iterations.

    The RTT feeding this re-pick comes only from CLEAN observations:
    the supervisor's drift detector never issues a verdict on a
    PIPELINED dispatch (in-flight depth > 1), whose wall includes
    queuing behind the dispatches it overlapped — once overlapped,
    wall per dispatch is no longer RTT-dominated in either direction,
    and treating it as an RTT sample would false-trigger the >2x
    re-measure (supervisor._note_wall)."""
    import jax

    if jax.default_backend() == "cpu":
        return 1
    raw = dispatch_rtt_ms() / 8.0
    for k in (4, 8, 16):
        if raw <= k:
            return k
    return 32


def remeasure_dispatch_rtt() -> float:
    """Drop the cached per-backend RTT and measure again — the
    dispatch supervisor's drift response (VERDICT r5 "Next round" #7:
    the tunnel RTT drifted 124 -> 255 ms mid-session, stranding the
    steps-per-dispatch K sized at session start). The env override
    still wins (dispatch_rtt_ms reads it first), so a pinned
    $PINT_TPU_DISPATCH_RTT_MS cannot be drifted away from. Callers on
    an accelerator backend must bound this (the probe dispatch hangs
    on a wedged tunnel) — the supervisor runs it under its guarded
    worker."""
    _RTT_MS.clear()
    return dispatch_rtt_ms()


# ------------------------------------------------- dispatch supervision


def dispatch_deadline_ms() -> Optional[float]:
    """Hard watchdog-deadline override for every supervised dispatch
    [ms] ($PINT_TPU_DISPATCH_DEADLINE_MS). Default None: the
    supervisor predicts a deadline from measured RTT x
    steps-per-dispatch plus a first-call compile allowance. The
    override is PER DISPATCH: a pipelined (async) dispatch issued at
    in-flight depth d still waits out its d-1 predecessors before
    its own work starts, so its effective watchdog is d x this value
    (supervisor._deadline_s) — the bound an operator pins applies to
    each dispatch's own window, not to a whole pipeline."""
    v = _env_number("PINT_TPU_DISPATCH_DEADLINE_MS", None)
    return None if v is None else float(v)


def dispatch_retries() -> int:
    """Retries for TRANSIENT dispatch errors (connection resets, XLA
    UNAVAILABLE) before failing over ($PINT_TPU_DISPATCH_RETRIES).
    Timeouts never retry — another attempt against a backend that
    just hung costs another full deadline."""
    return max(0, int(_env_number("PINT_TPU_DISPATCH_RETRIES", 2,
                                  cast=int)))


def dispatch_backoff_ms() -> float:
    """Base retry backoff [ms], doubled per attempt with +0-50%
    jitter ($PINT_TPU_DISPATCH_BACKOFF_MS)."""
    return max(0.0, float(_env_number("PINT_TPU_DISPATCH_BACKOFF_MS",
                                      50.0)))


def dispatch_compile_allowance_ms() -> float:
    """Extra deadline budget for the FIRST dispatch per call-site key
    ($PINT_TPU_DISPATCH_COMPILE_ALLOWANCE_MS): remote compiles over
    the axon tunnel run multi-minute (measured round 4), and a cold
    compile must not read as a hang. Default 10 min."""
    return max(0.0, float(_env_number(
        "PINT_TPU_DISPATCH_COMPILE_ALLOWANCE_MS", 600_000.0)))


def donation_enabled(flag: Optional[bool] = None) -> bool:
    """Buffer donation at the dispatch boundary ($PINT_TPU_DONATE,
    default ON): jitted programs whose iterated state round-trips the
    device — the fit loop's (th, tl) parameter pairs, the serve batch
    kernels' alias-exact inputs — are compiled with donate_argnums so
    XLA reuses the input buffers for the outputs instead of copying
    through HBM every dispatch. Donation is only ever applied at
    sites whose donated arguments are rebuilt fresh per dispatch
    (graftlint G11 flags any read of a donated buffer after its
    dispatch), and the CPU equality oracles in
    tests/test_device_fitter.py prove donation changes nothing."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("PINT_TPU_DONATE", "").lower() \
        not in ("off", "false", "0")


def whole_fit_enabled(flag: Optional[bool] = None) -> bool:
    """Whole-fit-on-device default for Fitter.auto's device route
    ($PINT_TPU_WHOLE_FIT): run the ENTIRE downhill fit — damping,
    acceptance, convergence — inside one deadline-supervised
    lax.while_loop dispatch instead of K-chained chunks. Default ON
    on accelerator backends (one dispatch = one RTT for the whole
    fit), OFF on the CPU backend where dispatch is ~free and the
    plain step keeps compile time down. Explicit
    DeviceDownhillGLSFitter(whole_fit=...) / fit_toas(whole_fit=...)
    always wins."""
    import jax

    if flag is not None:
        return bool(flag)
    env = os.environ.get("PINT_TPU_WHOLE_FIT", "").lower()
    if env in ("on", "true", "1"):
        return True
    if env in ("off", "false", "0"):
        return False
    return jax.default_backend() != "cpu"


def breaker_threshold() -> int:
    """Consecutive dispatch failures that trip a backend's circuit
    breaker OPEN ($PINT_TPU_BREAKER_THRESHOLD)."""
    return max(1, int(_env_number("PINT_TPU_BREAKER_THRESHOLD", 3,
                                  cast=int)))


def breaker_cooldown_s() -> float:
    """Seconds an OPEN breaker short-circuits dispatches before the
    next bounded half-open re-probe ($PINT_TPU_BREAKER_COOLDOWN_S);
    doubles per failed re-probe, capped near the committed watcher's
    ~8-min poll cadence."""
    return max(0.0, float(_env_number("PINT_TPU_BREAKER_COOLDOWN_S",
                                      60.0)))


def breaker_probe_timeout_s() -> float:
    """Kill timer on the half-open subprocess backend probe
    ($PINT_TPU_BREAKER_PROBE_TIMEOUT_S; same order as the watcher's
    PROBE_TIMEOUT — a live tunnel answers in seconds, a wedged one
    never does)."""
    return max(1.0, float(_env_number(
        "PINT_TPU_BREAKER_PROBE_TIMEOUT_S", 150.0)))


def solve_streaming() -> int:
    """TOA-count threshold above which ``Fitter.auto`` picks the
    matrix-free streaming GLS path (``parallel.streaming``) over the
    dense device/host fitters ($PINT_TPU_STREAM_MIN_TOA; 0 disables
    the route entirely). Default 200k: comfortably above the largest
    dense shape the device memory plan was validated at (the 131k
    sharded oracle) and below where a dense (N, p+q) whitened design
    stops fitting in HBM. Validated finite positive int — a bad
    value warns once and falls back (the
    ``dispatch_rtt_override_ms`` convention)."""
    v = _env_number("PINT_TPU_STREAM_MIN_TOA", 200_000, cast=int)
    v = int(v)
    if v < 0:
        raw = os.environ.get("PINT_TPU_STREAM_MIN_TOA")
        key = ("PINT_TPU_STREAM_MIN_TOA", f"range:{raw}")
        if key not in _WARNED_ENV:
            _WARNED_ENV.add(key)
            from pint_tpu.logging import log

            log.warning("$PINT_TPU_STREAM_MIN_TOA=%r is negative; "
                        "using 200000", raw)
        return 200_000
    return v


def stream_chunk(ntoa: int) -> int:
    """Streaming-accumulator chunk length for an ``ntoa``-TOA fit
    ($PINT_TPU_STREAM_CHUNK): a POWER OF TWO, because the chunk
    length is the compile key of the chunk kernel — the whole-fit-K
    quantization discipline (auto_steps_per_dispatch): a raw
    ceil(N/k) would compile one executable per distinct N, while the
    quantized set stays bounded. Default: the smallest power of two
    >= ntoa/8 clamped to [4096, 65536] (>=8 chunks keeps per-chunk
    padding waste <12.5%; the 65536 cap bounds the (chunk, p+q)
    device working set). A pinned override is validated (finite
    positive int, warn-and-ignore otherwise) and rounded UP to the
    nearest power of two in [256, 131072] so a typo can never
    un-quantize the compile keys."""
    env = _env_number("PINT_TPU_STREAM_CHUNK", None, cast=int)
    if env is not None:
        v = int(env)
        if v <= 0:
            raw = os.environ.get("PINT_TPU_STREAM_CHUNK")
            key = ("PINT_TPU_STREAM_CHUNK", f"range:{raw}")
            if key not in _WARNED_ENV:
                _WARNED_ENV.add(key)
                from pint_tpu.logging import log

                log.warning("$PINT_TPU_STREAM_CHUNK=%r is not a "
                            "positive chunk length; using the auto "
                            "size", raw)
        else:
            k = 256
            while k < v and k < 131072:
                k *= 2
            return k
    k = 4096
    target = -(-int(ntoa) // 8)
    while k < target and k < 65536:
        k *= 2
    return k


def solve_device(ntoa: int):
    """Device for the host fitters' linear-solve kernels, or None for
    the default backend. Small problems stay on the host CPU when the
    default backend is an accelerator: every accelerator dispatch has
    a fixed latency (∼0.1–0.25 s round-trip over the axon TPU tunnel,
    ∼0.1–1 ms on a local chip) that dwarfs a tiny solve — a 62-TOA WLS
    fit measured 3.4 s over the tunnel vs 6 ms on host. Threshold:
    $PINT_TPU_HOST_SOLVE_MAX_TOA (default 8192 when the axon tunnel
    env is present, else 1024; 0 disables routing). Fitter.auto uses
    the same policy to pick host fitters over the device-resident
    downhill fitter for small problems."""
    import jax

    if jax.default_backend() == "cpu":
        return None
    try:
        thresh = int(os.environ.get("PINT_TPU_HOST_SOLVE_MAX_TOA", -1))
    except ValueError:
        thresh = -1
    if thresh < 0:
        thresh = 8192 if os.environ.get("PALLAS_AXON_POOL_IPS") \
            else 1024
    if thresh == 0 or ntoa >= thresh:
        return None
    return jax.devices("cpu")[0]


def enable_user_compile_cache() -> Optional[str]:
    """Persistent XLA compile cache for the CLI entry points:
    ~/.cache/pint_tpu/xla ($PINT_TPU_JIT_CACHE overrides; "0"
    disables). Called from each script's main() — NOT at library
    import (repointing jax's global cache on import would hijack
    whatever cache the embedding application configured). Repeat
    pintempo/photonphase runs then skip their dominant compile cost
    the way the test suite and bench already do."""
    d = os.path.join(os.path.expanduser("~"), ".cache", "pint_tpu",
                     "xla")
    return enable_compile_cache("PINT_TPU_JIT_CACHE", d)


def hybrid_jac_enabled(flag: Optional[bool] = None) -> bool:
    """The ONE parser for $PINT_TPU_HYBRID_JAC (default ON): shared by
    parallel.fit_step and TimingModel._get_compiled_jac so the device
    step and the host-fitter design matrix can never disagree about
    the Jacobian route under the same environment."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("PINT_TPU_HYBRID_JAC", "").lower() \
        not in ("off", "false", "0")


def solve_scope(ntoa: int):
    """Context manager form of solve_device: jax.default_device(cpu)
    for small problems on an accelerator backend, else a no-op. All
    jnp.asarray placements of the solve inputs must happen INSIDE the
    scope — converting first would ship them to the accelerator (over
    the tunnel) only to pull them back for the pinned solve."""
    import contextlib

    import jax

    dev = solve_device(ntoa)
    return jax.default_device(dev) if dev is not None \
        else contextlib.nullcontext()


def _host_cache_tag() -> str:
    """Cache-subdir tag keyed by the host CPU's feature set. CPU-backend
    cache entries embed machine code for the compiling host's ISA
    extensions; reusing them on a host with different features risks
    SIGILL (XLA warns exactly this when a cache dir travels between
    heterogeneous driver machines — observed in the round-4 driver
    bench run). TPU-backend entries are device code and host-portable,
    but they are compiled under a distinct jax platform key, so keying
    the whole dir by host features only costs one recompile per new
    host, never correctness."""
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    h = hashlib.sha256(
        (platform.machine() + ":" + feats).encode()).hexdigest()[:10]
    return f"{platform.machine()}-{h}"


def enable_compile_cache(env_var: str, default_dir: str) -> Optional[str]:
    """Point jax's persistent XLA compilation cache at a host-keyed
    subdirectory of ``default_dir`` (override the base with the named
    env var; value "0" disables). Shared by tests/conftest.py and
    bench.py — the suite and the benchmark are both compile-dominated
    on a cold start. The subdirectory is keyed by the host CPU feature
    set (see _host_cache_tag) so a cache dir reused across
    heterogeneous driver hosts can never serve foreign-ISA binaries.
    Returns the dir used."""
    import jax

    base = os.environ.get(env_var, default_dir)
    if base == "0":
        return None
    cache_dir = os.path.join(base, _host_cache_tag())
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return cache_dir


def datadir() -> Path:
    """Package directory (embedded runtime data lives in modules)."""
    return Path(__file__).resolve().parent


def runtimefile(name: str) -> Path:
    """Path of a runtime data file; checks the override dirs first
    (reference: config.runtimefile)."""
    for env in ("PINT_TPU_CLOCK_DIR", "PINT_TPU_EPHEM_DIR"):
        d = os.environ.get(env)
        if d and (Path(d) / name).exists():
            return Path(d) / name
    p = datadir() / "data" / name
    if p.exists():
        return p
    raise FileNotFoundError(f"no runtime file {name!r}")


def clock_dir() -> Optional[Path]:
    d = os.environ.get("PINT_TPU_CLOCK_DIR")
    return Path(d) if d else None


def ephem_dir() -> Optional[Path]:
    d = os.environ.get("PINT_TPU_EPHEM_DIR")
    return Path(d) if d else None


def obs_override() -> Optional[Path]:
    d = os.environ.get("PINT_TPU_OBS_OVERRIDE")
    return Path(d) if d else None


# ---------------------------------------------------------- serving


def serve_bucket_edges() -> tuple:
    """TOA-count bucket edges for the serve layer's shape classes
    (pint_tpu.serve.bucket): requests pad up to the smallest edge
    that fits, so compiled-executable count is bounded by the edge
    count. Default: powers of two 64..16384 (the 64 floor keeps tiny
    requests from fragmenting into many micro-classes; 16384 covers
    the NANOGrav-scale stress shape). Override with
    $PINT_TPU_SERVE_BUCKETS, a comma-separated ascending int list."""
    raw = os.environ.get("PINT_TPU_SERVE_BUCKETS")
    if raw:
        try:
            edges = tuple(sorted(int(x) for x in raw.split(",")
                                 if x.strip()))
            if edges and all(e > 0 for e in edges):
                return edges
        except ValueError:
            pass
        if ("PINT_TPU_SERVE_BUCKETS", raw) not in _WARNED_ENV:
            _WARNED_ENV.add(("PINT_TPU_SERVE_BUCKETS", raw))
            from pint_tpu.logging import log

            log.warning("unparsable $PINT_TPU_SERVE_BUCKETS=%r; "
                        "using defaults", raw)
    return tuple(64 * 2 ** k for k in range(9))  # 64..16384


def serve_window_s() -> float:
    """Coalescing window of the threaded serving loop [s]: how long
    the scheduler holds the first request of a burst open for
    batchmates. Default 5 ms — several multiples of a local dispatch
    RTT (so coalescing actually wins) while staying far inside any
    human-facing latency budget. $PINT_TPU_SERVE_WINDOW_MS
    overrides (milliseconds)."""
    return float(_env_number("PINT_TPU_SERVE_WINDOW_MS", 5.0)) / 1e3


def serve_max_batch() -> int:
    """Max requests coalesced into one dispatch (the batch axis also
    pads to a power of two <= this). $PINT_TPU_SERVE_MAX_BATCH."""
    return max(1, int(_env_number("PINT_TPU_SERVE_MAX_BATCH", 64,
                                  cast=int)))


def serve_queue_cap() -> int:
    """Admission-queue capacity; a full queue rejects submits with
    ServeOverload (backpressure). $PINT_TPU_SERVE_QUEUE_CAP."""
    return max(1, int(_env_number("PINT_TPU_SERVE_QUEUE_CAP", 4096,
                                  cast=int)))


def tenant_qps() -> float:
    """Per-tenant admission rate for the serve layer's token-bucket
    quotas [requests/s] ($PINT_TPU_TENANT_QPS). 0 (the default)
    disables quota enforcement entirely — a single-tenant deployment
    pays no bookkeeping. Each tenant's bucket refills at this rate up
    to ``tenant_burst()`` tokens; a drained bucket sheds the submit
    with ``TenantOverQuota`` (labeled in the admission counters,
    never a silent drop)."""
    return max(0.0, float(_env_number("PINT_TPU_TENANT_QPS", 0.0)))


def tenant_burst() -> float:
    """Token-bucket capacity per tenant ($PINT_TPU_TENANT_BURST):
    how large a burst a tenant may land instantaneously before the
    refill rate (``tenant_qps``) gates it. Default: 2x the rate
    (>= 1), the classic burst allowance."""
    qps = tenant_qps()
    return max(1.0, float(_env_number("PINT_TPU_TENANT_BURST",
                                      max(1.0, 2.0 * qps))))


def shed_policy() -> str:
    """Load-shedding policy when the admission queue is at capacity
    ($PINT_TPU_SHED_POLICY):

    - "deadline" (default): deadline-aware — shed a QUEUED request
      that will miss its deadline anyway (its remaining budget is
      smaller than the router-predicted wait), admitting the
      newcomer in its place; a newcomer that cannot make its own
      deadline is shed instead; only when nobody is provably doomed
      does the submit fall back to plain backpressure rejection.
      Never sheds a request that can still make it.
    - "reject": classic backpressure — the newcomer is rejected with
      ServeOverload, queued requests are never touched.
    """
    v = os.environ.get("PINT_TPU_SHED_POLICY", "deadline").lower()
    if v not in ("deadline", "reject"):
        if ("PINT_TPU_SHED_POLICY", v) not in _WARNED_ENV:
            _WARNED_ENV.add(("PINT_TPU_SHED_POLICY", v))
            from pint_tpu.logging import log

            log.warning("unknown $PINT_TPU_SHED_POLICY=%r; using "
                        "'deadline'", v)
        return "deadline"
    return v


def aot_dir():
    """Directory for AOT-exported serve bucket executables
    ($PINT_TPU_AOT_DIR; None = disabled). A ServeEngine given this
    dir exports every shape class it compiles (jax.export StableHLO
    artifacts + a manifest) and a fresh engine restores them at
    construction, so a process restart serves its first bucketed
    request without re-tracing or re-compiling the serve kernels
    (the XLA binary compile of a restored module is paid at RESTORE
    time, seeded by the feature-keyed persistent jit cache — never
    on the first request)."""
    d = os.environ.get("PINT_TPU_AOT_DIR")
    return d if d else None


def journal_path():
    """Append-only serve request journal ($PINT_TPU_JOURNAL; None =
    disabled): every journalable admission is recorded before
    dispatch and acknowledged on completion, so a cold restart can
    replay exactly the unacknowledged entries
    (``ServeEngine.replay``). The daemon (scripts/pint_serve) records
    its raw JSONL request lines through the same machinery."""
    p = os.environ.get("PINT_TPU_JOURNAL")
    return p if p else None


def serve_drain_timeout_s() -> float:
    """Bound on the graceful-shutdown drain
    ($PINT_TPU_SERVE_DRAIN_TIMEOUT_S, default 30 s): on SIGTERM the
    engine keeps dispatching queued work until this deadline, then
    sheds the remainder with an explicit labeled response per
    request — a shutdown must never silently drop accepted work, and
    must never hang forever either."""
    return max(0.0, float(_env_number(
        "PINT_TPU_SERVE_DRAIN_TIMEOUT_S", 30.0)))


def chain_chunk_steps(nsteps: int, thin: int = 1) -> int:
    """MCMC steps chained inside ONE whole-chain-on-device dispatch
    (pint_tpu.sampling): the smallest power of two covering
    ``nsteps``, clamped to [16, 256] and rounded up to a multiple of
    ``thin`` — the chain analog of the whole-fit K quantization
    (auto_steps_per_dispatch): K is part of the scan program's
    compile key, so a raw nsteps would compile one executable per
    distinct chain length, while the quantized set bounds it to 5
    entries and the per-chunk RUNTIME budget argument keeps extra
    compiled steps from executing. Longer chains run as chunked
    multi-dispatch (each chunk its own supervised deadline, so serve
    drains and SIGTERM stay bounded). $PINT_TPU_CHAIN_CHUNK pins the
    chunk size (still rounded to a thin multiple)."""
    env = _env_number("PINT_TPU_CHAIN_CHUNK", None, cast=int)
    if env is not None:
        k = max(1, int(env))
    else:
        k = 16
        while k < int(nsteps) and k < 256:
            k *= 2
    thin = max(1, int(thin))
    return ((k + thin - 1) // thin) * thin


def gwb_chunk() -> int:
    """(log10_A, gamma) grid points evaluated per supervised GWB
    sweep dispatch (pint_tpu.pta.gwb): the chunk is the failover /
    deadline / journal-progress boundary, NOT a vectorization width
    (the outer kernel lax.maps the chunk so only one (Npsr*m)^2
    Schur system is live at a time). Power of two in [1, 64] —
    part of the sweep program's compile key, same quantization
    rationale as chain_chunk_steps. $PINT_TPU_GWB_CHUNK pins it
    (rounded UP to the nearest power of two, warn-and-ignore on bad
    values)."""
    env = _env_number("PINT_TPU_GWB_CHUNK", None, cast=int)
    if env is None:
        return 8
    k = int(env)
    if k < 1 or k > 64:
        key = ("PINT_TPU_GWB_CHUNK", str(env))
        if key not in _WARNED_ENV:
            _WARNED_ENV.add(key)
            from pint_tpu.logging import log

            log.warning("$PINT_TPU_GWB_CHUNK=%r outside [1, 64]; "
                        "using default 8", env)
        return 8
    return 1 << (k - 1).bit_length()


def journal_compact_bytes() -> int:
    """Journal size past which ``RequestJournal`` auto-compacts
    (rewrites itself to just the unacknowledged admit records;
    $PINT_TPU_JOURNAL_COMPACT_BYTES, default 16 MiB, 0 disables): a
    long-lived deployment's append-only journal otherwise grows
    without bound even though the replay set stays tiny. Compaction
    is atomic (tmp + rename, fsynced) so a crash mid-compaction
    leaves the previous journal intact."""
    return max(0, int(_env_number("PINT_TPU_JOURNAL_COMPACT_BYTES",
                                  16 * 1024 * 1024, cast=int)))


# ------------------------------------------------ precision routing


def f32_mode(env_name: str,
             flag: Optional[bool] = None) -> Optional[bool]:
    """The ONE tri-state parser for the f32/f64 route env vars
    ($PINT_TPU_ANCHORED / $PINT_TPU_JAC / $PINT_TPU_GLS_MATMUL —
    ISSUE 11 satellite, the dispatch_rtt_override_ms convention):
    an explicit ``flag`` wins; else True for f32-ish values, False
    for f64-ish ones, None (= auto: f32 on TPU) when unset — and an
    unrecognized value WARNS once and is ignored (treated as unset)
    instead of silently falling through to auto, which is what the
    raw ``os.environ`` reads in parallel/fit_step.py used to do."""
    if flag is not None:
        return bool(flag)
    raw = os.environ.get(env_name, "")
    v = raw.lower()
    if v in ("f32", "float32", "on", "true", "1"):
        return True
    if v in ("f64", "float64", "off", "false", "0"):
        return False
    if v and (env_name, raw) not in _WARNED_ENV:
        _WARNED_ENV.add((env_name, raw))
        from pint_tpu.logging import log

        log.warning("unparsable $%s=%r (want f32/f64/on/off); "
                    "using the backend default", env_name, raw)
    return None


def no_pallas(flag: Optional[bool] = None) -> bool:
    """Validated $PINT_TPU_NO_PALLAS parser (ISSUE 11 satellite —
    replaces the raw presence check in ops/pallas_kernels.py):
    truthy values disable the Pallas photon kernels, falsy/unset
    keep them; an unrecognized value warns once and is IGNORED
    (kernels stay enabled), per the warn-and-ignore convention."""
    return _env_bool("PINT_TPU_NO_PALLAS", flag,
                     context="keeping the Pallas kernels enabled")


# ---------------------------------------------------- observability


def trace_enabled() -> bool:
    """Structured span tracing ($PINT_TPU_TRACE, default OFF): when
    on, every serve request / supervised dispatch / device fit emits
    causally-linked spans into the process tracer's ring buffer
    (``pint_tpu.obs``), exportable as Chrome trace-event JSON
    (Perfetto / chrome://tracing). Off, the hot path pays a single
    branch per instrumentation point — the <1% north-star contract
    measured in bench.py's ``obs`` block."""
    return os.environ.get("PINT_TPU_TRACE", "").lower() in (
        "1", "on", "true", "yes")


def trace_stream_path():
    """JSONL span-stream path ($PINT_TPU_TRACE_STREAM; None =
    disabled): completed spans/events are appended as one JSON object
    per line AS THEY COMPLETE, in addition to the ring buffer — the
    ``pint_serve`` daemon's live-tail mode (a crash loses at most the
    line being written, unlike a ring that dies with the process).
    Implies tracing even without $PINT_TPU_TRACE."""
    p = os.environ.get("PINT_TPU_TRACE_STREAM")
    return p if p else None


def trace_ring_size() -> int:
    """Span-ring capacity ($PINT_TPU_TRACE_RING, default 16384):
    the most recent completed spans/events kept in memory for export
    and for flight-recorder dumps. Bounded so a long-lived serving
    process never grows; at serving rates (a few spans per BATCH,
    not per TOA) the default covers minutes of history."""
    return max(256, int(_env_number("PINT_TPU_TRACE_RING", 16384,
                                    cast=int)))


def flight_dir():
    """Flight-recorder dump directory ($PINT_TPU_FLIGHT_DIR; None =
    disabled): on breaker-open, shed-burst, shutdown drain, or an
    unhandled serve-engine exception, the tracer's recent-span ring
    is dumped to a timestamped JSON file there — pairing with the
    request journal so a post-mortem has both *what was pending* and
    *what the system was doing*. Arming the flight recorder turns on
    span RECORDING (ring only) even when $PINT_TPU_TRACE is off."""
    d = os.environ.get("PINT_TPU_FLIGHT_DIR")
    return d if d else None


def slo_enabled() -> bool:
    """SLO burn-rate watchdog armed? ($PINT_TPU_SLO, default OFF.)
    Any value slo_specs() can resolve to a non-empty spec list arms
    it: a truthy flag (the default spec set), inline JSON, or a JSON
    file path. Off (unset/falsy) costs nothing — no sampling thread,
    no ring."""
    raw = os.environ.get("PINT_TPU_SLO", "")
    if raw.lower() in ("", "0", "off", "false", "no"):
        return False
    return bool(slo_specs())


def slo_interval_s() -> float:
    """SLO self-sampling interval [s] ($PINT_TPU_SLO_INTERVAL_S,
    default 10): how often the watchdog snapshots the registry into
    its time-series ring. Validated finite positive — a zero or
    negative interval would spin the sampler; warn-and-ignore per
    the dispatch_rtt_override_ms convention."""
    return _env_positive_float("PINT_TPU_SLO_INTERVAL_S", 10.0)


def slo_specs() -> list:
    """Validated SLO spec list from $PINT_TPU_SLO (ISSUE 11):

    - a truthy flag ("1"/"on"/"true"/"yes") -> the default spec set
      (obs.slo.default_specs: e2e p99 per kind, shed rate, dispatch
      overhead_frac);
    - a JSON array (inline, or the contents of the file the value
      points at) -> custom specs, each entry validated by
      SLOSpec.from_dict — an invalid ENTRY warns and is dropped
      (warn-and-ignore, never a mis-armed watchdog), an unreadable
      value warns and yields [] (watchdog stays off).
    """
    import json

    from pint_tpu.obs.slo import SLOSpec, default_specs

    raw = os.environ.get("PINT_TPU_SLO", "")
    v = raw.strip()
    if v.lower() in ("", "0", "off", "false", "no"):
        return []
    if v.lower() in ("1", "on", "true", "yes"):
        return default_specs()
    text = v
    if not v.startswith(("[", "{")):
        try:
            with open(v, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            if ("PINT_TPU_SLO", raw) not in _WARNED_ENV:
                _WARNED_ENV.add(("PINT_TPU_SLO", raw))
                from pint_tpu.logging import log

                log.warning("$PINT_TPU_SLO=%r is neither a flag, "
                            "JSON, nor a readable file; SLO "
                            "watchdog stays off", raw)
            return []
    try:
        entries = json.loads(text)
        if isinstance(entries, dict):
            entries = [entries]
    except ValueError:
        if ("PINT_TPU_SLO", raw) not in _WARNED_ENV:
            _WARNED_ENV.add(("PINT_TPU_SLO", raw))
            from pint_tpu.logging import log

            log.warning("unparsable $PINT_TPU_SLO JSON; SLO "
                        "watchdog stays off")
        return []
    out = []
    for e in entries:
        try:
            out.append(SLOSpec.from_dict(e))
        except (ValueError, TypeError) as exc:
            key = ("PINT_TPU_SLO", f"entry:{e!r}"[:200])
            if key not in _WARNED_ENV:
                _WARNED_ENV.add(key)
                from pint_tpu.logging import log

                log.warning("dropping invalid SLO spec entry: %s",
                            exc)
    return out


def _warn_env_range(name: str, default):
    """Once-per-distinct-value out-of-range warning (the shared tail
    of every validated numeric parser below)."""
    raw = os.environ.get(name)
    key = (name, f"range:{raw}")
    if key not in _WARNED_ENV:
        _WARNED_ENV.add(key)
        from pint_tpu.logging import log

        log.warning("$%s=%r is out of range; using %r", name, raw,
                    default)


def _env_positive_float(name: str, default: float,
                        minimum_exclusive: float = 0.0) -> float:
    """Validated finite float env knob > ``minimum_exclusive`` (the
    ``slo_interval_s`` convention): warn-and-ignore on anything
    else. THE one home of the bounded-float boilerplate — new
    threshold knobs extend this, not re-implement it."""
    import math

    v = float(_env_number(name, default))
    if not math.isfinite(v) or v <= minimum_exclusive:
        _warn_env_range(name, default)
        return default
    return v


def _env_bool(name: str, flag=None, default: bool = False,
              context: str = "") -> bool:
    """Shared tri-state on/off env parser (the warn-and-ignore
    convention): explicit ``flag`` wins; truthy/falsy values map;
    anything else warns once and yields ``default``. The bool
    sibling of ``_env_positive_float``."""
    if flag is not None:
        return bool(flag)
    raw = os.environ.get(name, "")
    v = raw.lower()
    if v in ("1", "on", "true", "yes"):
        return True
    if v in ("", "0", "off", "false", "no"):
        return False
    if (name, raw) not in _WARNED_ENV:
        _WARNED_ENV.add((name, raw))
        from pint_tpu.logging import log

        log.warning("unparsable $%s=%r (want on/off)%s", name, raw,
                    f"; {context}" if context else "")
    return default


def _env_nonneg_int(name: str, default: int) -> int:
    """Validated non-negative int env knob; warn-and-ignore
    otherwise (the int sibling of ``_env_positive_float``)."""
    v = int(_env_number(name, default, cast=int))
    if v < 0:
        _warn_env_range(name, default)
        return default
    return v


def health_enabled(flag: Optional[bool] = None) -> bool:
    """In-trace numerical-health taps armed? ($PINT_TPU_HEALTH,
    default OFF — the same opt-in stance as $PINT_TPU_TRACE /
    $PINT_TPU_SLO.) When armed, the device kernels return a cheap
    in-trace health vector as extra scalars (non-finite counts,
    max residual in sigma, CG effort) and the process
    ``obs.health.HealthMonitor`` evaluates it against the validated
    thresholds below. Disarmed, the taps compile to NOTHING: the
    health flag is a static build/compile-key bit (like donation),
    so the disarmed executables are byte-identical to pre-health
    ones. An explicit ``flag`` wins; an unrecognized env value warns
    once and is ignored (stays off)."""
    return _env_bool("PINT_TPU_HEALTH", flag,
                     context="health taps stay off")


def shadow_rate() -> int:
    """Shadow-oracle drift sampling rate ($PINT_TPU_SHADOW_RATE;
    default 0 = off): every Nth successful supervised dispatch of a
    shadow-capable key replays the completed solve on the existing
    numpy mirrors in a BACKGROUND thread and records device-vs-host
    drift in sigma into the registry drift histogram — the
    production-grade answer to "is emulated f64 still holding" at
    sizes where no dense oracle can run. Validated non-negative int
    (e.g. 256 = one replay per 256 dispatches per key);
    warn-and-ignore otherwise."""
    return _env_nonneg_int("PINT_TPU_SHADOW_RATE", 0)


def health_drift_sigma() -> float:
    """Shadow-oracle drift band [sigma] ($PINT_TPU_HEALTH_DRIFT_SIGMA;
    route-aware auto default): device-vs-host-mirror parameter drift
    beyond this many (reported) sigma is a ``numerics:drift``
    incident.

    The auto default follows the ACTIVE precision routes, because
    the sanctioned f32 production config (auto-on on TPU) carries a
    known, documented <1e-2-sigma quantization the shadow must
    tolerate, while an exact-f64 deployment should flag drift far
    below that:

    - f64 routes (no f32 env, non-TPU backend): 1e-5 — the measured
      f64 replay floor is ~1e-13 sigma and the emulated-f64 budget
      sits decades below the band, while an UNSANCTIONED f32
      demotion (a G9-class bug the config does not know about, so
      the band stays tight) measures ~1.5e-4 sigma — one decade
      above, so the detector demonstrably detects
      (tests/test_health.py);
    - any sanctioned f32 route active ($PINT_TPU_GLS_MATMUL /
      $PINT_TPU_JAC f32, or auto on a TPU backend): 2e-2 — above
      the documented f32 agreement bound, so a healthy production
      worker never flaps /healthz on its own sanctioned
      quantization while true garbage still flags.

    An explicit env value wins (validated finite positive,
    warn-and-ignore otherwise). Backend-init-safe: the auto
    resolution PEEKS jax's already-built client table only (the
    ``sample_device_memory`` discipline — this runs on the /healthz
    scrape path under the monitor lock, and backend discovery HANGS
    with no error on a wedged axon tunnel); an uninitialized
    backend reads as the f64 default."""
    auto = 1e-5
    backend = _backend_if_initialized()
    for env in ("PINT_TPU_GLS_MATMUL", "PINT_TPU_JAC"):
        mode = f32_mode(env)
        if mode is True or (mode is None and backend == "tpu"):
            auto = 2e-2
            break
    return _env_positive_float("PINT_TPU_HEALTH_DRIFT_SIGMA", auto)


def _backend_if_initialized():
    """jax.default_backend() ONLY when a backend client already
    exists; None otherwise — never triggers backend discovery (which
    hangs, not errors, on a wedged axon tunnel)."""
    import sys

    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None
    import jax

    return jax.default_backend()


def health_chi2_factor() -> float:
    """chi2 blow-up incident threshold
    ($PINT_TPU_HEALTH_CHI2_FACTOR, default 4.0): a step whose chi2
    GROWS past factor x the previous accepted value is a
    ``numerics:chi2_blowup`` incident (a descent method moving
    uphill is a numerics symptom, not an optimization choice).
    Validated finite > 1."""
    return _env_positive_float("PINT_TPU_HEALTH_CHI2_FACTOR", 4.0,
                               minimum_exclusive=1.0)


def health_resid_sigma() -> float:
    """Max |residual|/sigma incident threshold
    ($PINT_TPU_HEALTH_RESID_SIGMA, default 1e8): a single whitened
    residual past this is numeric garbage (overflow, a broken phase
    chain), not a bad timing model — genuinely mis-fit pulsars sit
    orders of magnitude below it. Validated finite positive."""
    return _env_positive_float("PINT_TPU_HEALTH_RESID_SIGMA", 1e8)


def health_cg_budget_frac() -> float:
    """CG effort incident threshold as a fraction of the runtime
    iteration budget ($PINT_TPU_HEALTH_CG_BUDGET_FRAC, default 1.0 =
    exhaustion only): iterations-used >= frac x budget is a
    ``numerics:cg_budget`` incident. Lower it to be warned while CG
    still converges but is working unusually hard. Validated finite
    in (0, 1] — a frac > 1 could never fire (iters <= budget), so it
    warns and falls back like every other out-of-range value."""
    v = _env_positive_float("PINT_TPU_HEALTH_CG_BUDGET_FRAC", 1.0)
    if v > 1.0:
        _warn_env_range("PINT_TPU_HEALTH_CG_BUDGET_FRAC", 1.0)
        return 1.0
    return v


def perf_enabled(flag: Optional[bool] = None) -> bool:
    """Dispatch-wall decomposition armed? ($PINT_TPU_PERF, default
    OFF — the $PINT_TPU_TRACE / $PINT_TPU_HEALTH opt-in stance.)
    When armed, every successful GUARDED supervised dispatch splits
    its wall into queue_wait / host_assembly / device_wall / collect
    (``obs.perf`` + ``RuntimeMetrics.perf``); disarmed, the
    supervisor pays one attribute read and a branch. The compile
    LEDGER is always on (compiles are rare, registry-only) — this
    flag arms only the per-dispatch work. An explicit ``flag`` wins;
    an unrecognized env value warns once and is ignored."""
    return _env_bool("PINT_TPU_PERF", flag,
                     context="perf decomposition stays off")


def lock_trace_enabled(flag: Optional[bool] = None) -> bool:
    """Traced-lock sanitizer armed? ($PINT_TPU_LOCK_TRACE, default
    OFF — the $PINT_TPU_TRACE / $PINT_TPU_HEALTH opt-in stance.)
    When armed, ``runtime.locks`` constructors hand out
    TracedLock/TracedRLock wrappers that record per-thread
    acquisition order into the process lock-order graph (cycle
    detection fires a ``lockorder:<edge>`` flight dump) and feed the
    ``pint_tpu_lock_*`` hold/contention histograms. Disarmed (the
    production default), the constructors return the BARE stdlib
    primitives — a true zero-cost passthrough, banded <1% on the
    north-star step in bench's ``obs`` block. An explicit ``flag``
    wins; an unrecognized env value warns once and is ignored."""
    return _env_bool("PINT_TPU_LOCK_TRACE", flag,
                     context="lock tracing stays off")


def compile_ledger_path():
    """JSONL persistence path for the compile ledger
    ($PINT_TPU_COMPILE_LEDGER; None = registry-only). Armed, every
    NEW ledgered key appends one JSON line (key, backend, compile
    wall, XLA cost/memory analysis, aot_restored, UTC stamp), and a
    restarted worker reads the file back as ``prior`` entries — the
    post-mortem record of exactly which executables existed and
    what each cost to build."""
    p = os.environ.get("PINT_TPU_COMPILE_LEDGER")
    return p if p else None


def profile_dir():
    """Profiler-window directory ($PINT_TPU_PROFILE_DIR; None =
    windows disarmed). Armed, ``obs.perf.request_window`` (the
    pint_serve ``{"kind": "profile"}`` answer) and the automatic
    one-shot incident windows (slo_burn / breaker-open) write one
    ``window-<utc>-<reason>/`` directory each: jax device trace +
    ``window.json`` metadata cross-linked to the triggering span ids
    and flight dump + a Perfetto-loadable ``spans.json``. Replaces
    bench.py's old raw read of the same env var."""
    d = os.environ.get("PINT_TPU_PROFILE_DIR")
    return d if d else None


def profile_max_s() -> float:
    """Hard bound on one profiler window's length [s]
    ($PINT_TPU_PROFILE_MAX_S, default 30): every requested window is
    clamped to it, so a typo'd ``{"kind": "profile", "seconds":
    86400}`` can never leave a device trace running for a day.
    Validated finite positive; warn-and-ignore otherwise (the
    ``slo_interval_s`` convention)."""
    return _env_positive_float("PINT_TPU_PROFILE_MAX_S", 30.0)


def metrics_port() -> Optional[int]:
    """Default /metrics exposition port for the daemon
    ($PINT_TPU_METRICS_PORT; None = off, 0 = ephemeral). The
    pint_serve --metrics-port flag overrides. Validated int in
    [0, 65535]; warn-and-ignore otherwise."""
    v = _env_number("PINT_TPU_METRICS_PORT", None, cast=int)
    if v is None:
        return None
    v = int(v)
    if not 0 <= v <= 65535:
        raw = os.environ.get("PINT_TPU_METRICS_PORT")
        key = ("PINT_TPU_METRICS_PORT", f"range:{raw}")
        if key not in _WARNED_ENV:
            _WARNED_ENV.add(key)
            from pint_tpu.logging import log

            log.warning("$PINT_TPU_METRICS_PORT=%r out of range; "
                        "metrics server stays off", raw)
        return None
    return v


def serve_pipeline_depth() -> int:
    """Max shape-class dispatches the serve scheduler keeps IN FLIGHT
    during one drain ($PINT_TPU_SERVE_PIPELINE, default 2): batch k+1
    is issued while batch k executes (double-buffering on jax's async
    dispatch; the supervisor's watchdog deadline scales by the
    in-flight depth). 1 = the synchronous drain (dispatch, read,
    scatter, next)."""
    return max(1, int(_env_number("PINT_TPU_SERVE_PIPELINE", 2,
                                  cast=int)))


# ------------------------------------------------ serve fleet (ISSUE 19)


def pool_spec() -> Optional[Tuple[str, ...]]:
    """Named capacity pools for the serve router ($PINT_TPU_POOLS,
    comma-separated; None = the classic {"device", "host"} pair).
    The spec must contain "device" and "host" — the engine's jitted
    executables and the numpy failover mirrors are structural, every
    extra name is an additional device-class pool with its own
    ``runtime.breaker`` instance and learned EWMA rates. Names must
    be identifier-ish ([a-z0-9_-]); a malformed spec warns once and
    is ignored (classic pools), never half-applied."""
    raw = os.environ.get("PINT_TPU_POOLS", "")
    if not raw:
        return None
    names = tuple(s.strip() for s in raw.split(",") if s.strip())
    ok = (len(names) == len(set(names)) and "device" in names
          and "host" in names
          and all(n.replace("_", "").replace("-", "").isalnum()
                  and n == n.lower() for n in names))
    if not ok:
        if ("PINT_TPU_POOLS", raw) not in _WARNED_ENV:
            _WARNED_ENV.add(("PINT_TPU_POOLS", raw))
            from pint_tpu.logging import log

            log.warning(
                "malformed $PINT_TPU_POOLS=%r (want unique "
                "lowercase comma-separated names including "
                "'device' and 'host'); using the classic pools",
                raw)
        return None
    return names


def fleet_lease_ttl_s() -> float:
    """Worker lease time-to-live [s] ($PINT_TPU_FLEET_LEASE_TTL_S,
    default 15): a fleet worker whose newest journal heartbeat is
    older than this is declared dead at the front's next sweep and
    its unacknowledged requests are re-homed onto survivors.
    Validated finite positive (warn-and-ignore otherwise)."""
    return _env_positive_float("PINT_TPU_FLEET_LEASE_TTL_S", 15.0)


def fleet_heartbeat_s() -> float:
    """Worker heartbeat period [s] ($PINT_TPU_FLEET_HEARTBEAT_S,
    default 5): each live worker appends a journal heartbeat record
    this often. Validated finite positive; values at or above the
    lease TTL are clamped to TTL/3 (a heartbeat slower than the
    lease it renews would expire every healthy worker)."""
    v = _env_positive_float("PINT_TPU_FLEET_HEARTBEAT_S", 5.0)
    ttl = fleet_lease_ttl_s()
    if v >= ttl:
        _warn_env_range("PINT_TPU_FLEET_HEARTBEAT_S", ttl / 3.0)
        return ttl / 3.0
    return v


def fleet_workers() -> int:
    """Default fleet size for ``pint_serve --fleet`` / the fleet
    bench ($PINT_TPU_FLEET_WORKERS, default 3, min 1). Validated
    positive int; warn-and-ignore otherwise."""
    v = int(_env_number("PINT_TPU_FLEET_WORKERS", 3, cast=int))
    if v < 1:
        _warn_env_range("PINT_TPU_FLEET_WORKERS", 3)
        return 3
    return v
