"""Registry-bound counters for the array likelihood plane.

Same contract as the supervisor's ``RuntimeMetrics`` (ISSUE 11): each
``PTAMetrics`` instance holds bound children of the process-global
``obs.metrics`` registry (``pint_tpu_pta_<name>_total``, labelled by
a per-instance scope), ``snapshot()`` is a derived view of the same
values, and every mutation goes through ``bump()`` — the counter
names are in graftlint's ``G13_COUNTER_NAMES`` vocabulary, so ad-hoc
``+= 1`` bookkeeping on them anywhere in the dispatch layer is
flagged.
"""

from __future__ import annotations

__all__ = ["PTAMetrics"]


class PTAMetrics:
    """Counters of the GWB likelihood plane:

    - ``block_assemblies``: per-pulsar inner-block batch dispatches
      (one per ``GWBLikelihood.build_blocks`` device call);
    - ``hd_outer_solves``: cross-correlated (Npsr*m)^2 outer-system
      factorizations actually evaluated (grid points swept);
    - ``gwb_solves``: supervised sweep-chunk dispatches.
    """

    _COUNTERS = ("gwb_solves", "block_assemblies", "hd_outer_solves")

    def __init__(self):
        from pint_tpu.obs import metrics as om

        self.scope = om.new_scope("pta")
        self._c = {
            name: om.counter(
                f"pint_tpu_pta_{name}_total",
                f"GWB plane {name.replace('_', ' ')}"
            ).child(scope=self.scope)
            for name in self._COUNTERS}

    def bump(self, name: str, n: int = 1):
        self._c[name].inc(n)

    def __getattr__(self, name: str):
        c = self.__dict__.get("_c", {})
        if name in c:
            return int(c[name].value())
        raise AttributeError(name)

    def snapshot(self) -> dict:
        """Derived view of the registry children — parity with the
        registry is test-asserted (tests/test_gwb.py)."""
        return {name: int(child.value())
                for name, child in self._c.items()}
