"""Compile-with-plan: explicit-sharding compilation of batch kernels.

The pulsar batch axis used to be "sharded" by device_put-ing inputs
with a NamedSharding and letting GSPMD partition ``jit(vmap(...))`` —
which on the CPU mesh LOST to single-device (BASELINE config 5's old
note): the partitioner keeps the batched Cholesky sequence serialized
on one logical program. Here the batch kernel is compiled through
``shard_map`` instead (reference: SNIPPETS [3], Titanax's
compile-with-plan helper): each device runs the per-slot kernel over
ITS contiguous block of pulsars — zero collectives, and the CPU
client executes the per-device partials concurrently, so the pulsar
axis finally scales. Explicit ``in_shardings``/``out_shardings`` on
the outer jit make placement part of the compiled plan (no resharding
on entry), and ``donate_argnums`` threads through to the XLA aliasing
table exactly like the serve cache's donation plumbing (SNIPPETS
[1]/[2]): only alias-exact positions may be donated, and donated
arrays must be rebuilt fresh per dispatch (graftlint G11).

This module is pure compilation planning — it never dispatches; the
supervised call sites (``parallel.pta.pta_solve``, ``pta.gwb``) own
the dispatch discipline (G6/G12).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

try:  # jax >= 0.4.35 staging area
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - newer jax promoted it
    from jax import shard_map  # type: ignore

__all__ = ["batch_sharding", "compile_with_plan", "mesh_fingerprint",
           "pad_batch", "plan_cache_clear"]

# plan cache: (name, mesh fingerprint, donate, ndims) -> compiled fn.
# Keyed on the mesh's device ids, not the Mesh object, so two Mesh
# wrappers over the same devices share one executable.
_PLANS: Dict[tuple, object] = {}


def mesh_fingerprint(mesh, axis: str):
    """Hashable identity of (mesh, axis) for the plan cache."""
    if mesh is None:
        return None
    return (tuple(int(d.id) for d in np.asarray(mesh.devices).flat),
            tuple(mesh.axis_names), str(axis))


def batch_sharding(mesh, axis: str, ndim: int) -> NamedSharding:
    """Leading-axis block sharding: dim 0 over ``axis``, the rest
    replicated — the one layout every batch kernel input/output here
    uses."""
    return NamedSharding(
        mesh, PartitionSpec(axis, *([None] * (ndim - 1))))


def pad_batch(arrs: Dict[str, np.ndarray], mesh, axis: str,
              ones_keys: Sequence[str] = ("nvec", "phi")) -> dict:
    """Pad every array's leading (pulsar) dim up to a mesh multiple so
    shard_map never sees a ragged block. Pad slots are fully-masked
    pulsars: unit ``nvec``/``phi`` (so logs and reciprocals stay
    finite), zeros elsewhere (valid = pvalid = 0 masks them out of
    every sum) — the same convention ``stack_problems`` uses for
    extra batch slots."""
    if mesh is None:
        return dict(arrs)
    nshard = mesh.shape[axis]
    P = next(iter(arrs.values())).shape[0]
    pad = (-P) % nshard
    if not pad:
        return dict(arrs)
    out = {}
    for k, v in arrs.items():
        v = np.asarray(v)
        fill = np.ones if k in ones_keys else np.zeros
        out[k] = np.concatenate(
            [v, fill((pad,) + v.shape[1:], dtype=v.dtype)], axis=0)
    return out


def compile_with_plan(fn, *, name: str, ndims_in: Sequence[int],
                      ndims_out: Sequence[int], mesh=None,
                      axis: str = "pulsar",
                      donate_argnums: Tuple[int, ...] = ()):
    """Compile a batch kernel under an explicit placement plan.

    ``fn`` maps leading-axis-batched arrays to leading-axis-batched
    outputs (a ``vmap`` of a per-slot kernel). Without a mesh this is
    plain ``jax.jit`` (plus donation); with one, ``fn`` is wrapped in
    ``shard_map`` over ``axis`` (every input/output block-sharded on
    dim 0, per-device blocks solved independently) and jitted with
    matching explicit in/out shardings so the compiled executable owns
    its layout end to end. ``ndims_in``/``ndims_out`` are the array
    ranks (specs and shardings are derived from them). Plans are
    cached per (name, mesh devices, axis, donation)."""
    donate = tuple(sorted(int(d) for d in donate_argnums))
    key = (name, mesh_fingerprint(mesh, axis), donate,
           tuple(ndims_in), tuple(ndims_out))
    got = _PLANS.get(key)
    if got is not None:
        return got
    if mesh is None:
        planned = jax.jit(fn, donate_argnums=donate)
    else:
        spec = PartitionSpec(axis)
        mapped = shard_map(
            fn, mesh=mesh,
            in_specs=tuple(spec for _ in ndims_in),
            out_specs=tuple(spec for _ in ndims_out),
            # no collectives anywhere in these kernels; skipping the
            # replication check keeps closed-over constants legal
            check_rep=False)
        planned = jax.jit(
            mapped,
            in_shardings=tuple(batch_sharding(mesh, axis, nd)
                               for nd in ndims_in),
            out_shardings=tuple(batch_sharding(mesh, axis, nd)
                                for nd in ndims_out),
            donate_argnums=donate)
    _PLANS[key] = planned
    return planned


def plan_cache_clear():
    """Drop every cached plan (tests that rebuild meshes)."""
    _PLANS.clear()
