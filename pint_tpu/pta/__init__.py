"""Array-level likelihood plane (ISSUE 17).

``pint_tpu.pta`` owns everything that treats the pulsar ARRAY — not a
single pulsar — as the unit of work:

- ``shard``: the compile-with-plan helper — explicit-sharding /
  donation compilation of batch kernels over the mesh's pulsar axis
  (shard_map per-device blocks, no GSPMD guessing), used by
  ``parallel.pta.pta_solve`` and the GWB block assembly.
- ``gwb``: the Hellings–Downs cross-correlated gravitational-wave-
  background likelihood — per-pulsar inner blocks from the SAME
  joint normal assembly the fitters use, a second-stage Schur
  complement over the (Npsr*m)^2 cross-correlated outer system, and
  a numpy mirror as the CPU oracle.
- ``metrics``: the plane's registry-bound counters
  (``block_assemblies`` / ``hd_outer_solves`` / ``gwb_solves``).

Serve integration (``GWBRequest``) lives in ``pint_tpu.serve``; this
package stays importable without the serve machinery.
"""

from pint_tpu.pta.gwb import (  # noqa: F401
    GWBLikelihood,
    gwb_basis,
    gwb_loglik_np,
    gwb_phi,
    hd_matrix,
    pulsar_positions,
)
from pint_tpu.pta.metrics import PTAMetrics  # noqa: F401
from pint_tpu.pta.shard import (  # noqa: F401
    batch_sharding,
    compile_with_plan,
    pad_batch,
)

__all__ = [
    "GWBLikelihood", "PTAMetrics", "batch_sharding",
    "compile_with_plan", "gwb_basis", "gwb_loglik_np", "gwb_phi",
    "hd_matrix", "pad_batch", "pulsar_positions",
]
