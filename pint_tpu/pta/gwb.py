"""Hellings–Downs cross-correlated GWB likelihood (ISSUE 17).

Reference: enterprise ``signal_base.LogLikelihood`` (basis-Woodbury
marginal likelihood) and van Haasteren & Vallisneri 2014 (1407.1838,
the low-rank GP formulation); PAPERS.md 2506.13866 for the
beyond-block-diagonal covariance structure.

Model: the array covariance is

    C = blockdiag(D_a) + U (Gamma ⊗ diag(phi_g)) U^T

where ``D_a = N_a + T_a P_a T_a^T`` is pulsar *a*'s own marginal
covariance (white noise + improper-flat timing model + its per-pulsar
noise bases — EXACTLY the system ``parallel.pta._assemble_normal``
builds), ``U = blockdiag(U_a)`` stacks a COMMON-span Fourier basis
(same frequencies, same reference epoch across pulsars — the
cross-correlation couples same-frequency coefficients), ``phi_g`` is
the common-process power-law PSD (``models.noise.powerlaw`` — the
same convention PLRedNoise uses) and ``Gamma`` the (Npsr, Npsr) HD
overlap-reduction matrix.

Blocked Woodbury, two stages:

- inner (per pulsar, sharded over the mesh's pulsar axis): from the
  SAME preconditioned joint-normal Cholesky ``_solve_one`` runs,
  compute ``A_a = U_a^T D_a^{-1} U_a``, ``x_a = U_a^T D_a^{-1} r_a``,
  ``rdr_a = r_a^T D_a^{-1} r_a`` (identically ``_solve_one``'s chi2)
  and ``ld_a = logdet D_a`` (up to the improper-prior constant);
- outer (one device, second-stage Schur complement): the (Npsr*m)^2
  cross-correlated system ``S = Gamma^{-1} ⊗ diag(1/phi_g)
  + blockdiag(A_a)``, giving

    log L = -1/2 [ sum_a rdr_a - x^T S^{-1} x + sum_a ld_a
                   + m logdet Gamma + Npsr sum_i log phi_g_i
                   + logdet S ]  (+ const).

In the block-diagonal limit ``Gamma = I`` this is EXACTLY the sum of
per-pulsar marginal likelihoods with the GWB basis appended as
ordinary red noise (tests/test_gwb.py asserts it against the existing
``_solve_one_np`` path). The GWB hyperparameters (log10_A, gamma)
enter ONLY through the outer stage, so the blocks are assembled once
and a whole (log10_A, gamma) detection sweep reuses them.

Every device call goes through the dispatch supervisor under an
``obs.span`` (G6/G12); hyperparameter grids, Gamma, the basis
frequencies and Tspan are runtime args (G10); everything is f64 (no
G9 registry entries needed). The numpy mirror (``gwb_loglik_np``) is
the CPU oracle and the host-failover target.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from pint_tpu.models.noise import (
    FYR,
    _tdb_seconds,
    create_fourier_design_matrix,
    powerlaw,
)
from pint_tpu.parallel.pta import (
    PulsarProblem,
    _assemble_normal,
    build_problem,
    stack_problems,
)
from pint_tpu.pta.metrics import PTAMetrics
from pint_tpu.pta.shard import batch_sharding, compile_with_plan, \
    pad_batch

__all__ = ["GWBLikelihood", "gwb_basis", "gwb_blocks_np",
           "gwb_loglik_np", "gwb_phi", "gwb_sweep_driver",
           "hd_matrix", "pulsar_positions"]


# -- geometry ----------------------------------------------------------

def pulsar_positions(models: Sequence) -> np.ndarray:
    """(P, 3) unit sky vectors from each model's astrometry
    (RAJ/DECJ, or ELONG/ELAT rotated by the mean obliquity — the HD
    matrix only consumes angular separations, so the frame just has
    to be common)."""
    out = []
    for m in models:
        raj = getattr(m, "RAJ", None)
        if raj is not None and raj.value is not None:
            a, d = raj.value, m.DECJ.value
            out.append((math.cos(d) * math.cos(a),
                        math.cos(d) * math.sin(a), math.sin(d)))
            continue
        elong = getattr(m, "ELONG", None)
        if elong is not None and elong.value is not None:
            lam, bet = elong.value, m.ELAT.value
            x = math.cos(bet) * math.cos(lam)
            y = math.cos(bet) * math.sin(lam)
            z = math.sin(bet)
            eps = math.radians(23.4392911)
            out.append((x, y * math.cos(eps) - z * math.sin(eps),
                        y * math.sin(eps) + z * math.cos(eps)))
            continue
        raise ValueError(
            "GWB likelihood needs sky positions: model "
            f"{getattr(m, 'name', '?')} has neither RAJ/DECJ nor "
            "ELONG/ELAT")
    return np.asarray(out, dtype=np.float64)


def hd_matrix(positions: np.ndarray) -> np.ndarray:
    """Hellings–Downs overlap-reduction matrix Gamma_ab for unit sky
    vectors (P, 3): with x = (1 - cos zeta_ab)/2,

        Gamma_ab = 3/2 x ln x - x/4 + 1/2   (a != b)
        Gamma_aa = 1                        (pulsar term: + 1/2)

    Symmetric positive definite for distinct sky positions (it is the
    correlation of an isotropic background plus the uncorrelated
    pulsar-term diagonal)."""
    pos = np.asarray(positions, dtype=np.float64)
    c = np.clip(pos @ pos.T, -1.0, 1.0)
    x = (1.0 - c) / 2.0
    safe = np.where(x > 0.0, x, 1.0)
    g = 1.5 * x * np.log(safe) - x / 4.0 + 0.5
    np.fill_diagonal(g, 1.0)
    return g


# -- common-process basis ----------------------------------------------

def gwb_basis(toas_list: Sequence, nfreq: int):
    """Common-span Fourier basis for the array: ONE reference epoch
    (the array's earliest TDB day) and ONE Tspan pin the frequencies
    and phases across pulsars — a per-pulsar span would rotate each
    sin/cos pair and the cross-correlation would couple mismatched
    modes (the same alignment contract the serve append path pins
    through ``noise_basis_weight(tspan=, tref_day=)``).

    Returns (U_list, fcols, tspan_s): per-pulsar (n_a, 2*nfreq) basis
    blocks, the per-COLUMN frequencies [Hz], and the common span [s].
    """
    for t in toas_list:
        if getattr(t, "tdb_day", None) is None:
            t.compute_TDBs()
    ref_day = min(float(np.min(t.tdb_day)) for t in toas_list)
    ts = [_tdb_seconds(t, ref_day=ref_day) for t in toas_list]
    lo = min(float(t.min()) for t in ts)
    hi = max(float(t.max()) for t in ts)
    tspan = hi - lo
    if not (tspan > 0.0):
        raise ValueError("GWB basis needs a positive common Tspan")
    U_list = []
    fcols = None
    for t in ts:
        U, fc = create_fourier_design_matrix(t, int(nfreq),
                                             Tspan=tspan)
        U_list.append(U)
        fcols = fc
    return U_list, np.asarray(fcols, dtype=np.float64), float(tspan)


def gwb_phi(fcols: np.ndarray, tspan: float, log10_A: float,
            gamma: float) -> np.ndarray:
    """Per-column prior weights [s^2] of the common process — the
    PLRedNoise convention exactly: powerlaw PSD times the bin width
    df = 1/Tspan."""
    return powerlaw(fcols, 10.0 ** float(log10_A), float(gamma)) \
        / float(tspan)


# -- inner stage: per-pulsar blocks (device kernel + numpy mirror) -----

def _gwb_block_one(M, F, phi, r, nvec, valid, pvalid, U):
    """One pulsar's GWB coupling blocks from the shared joint-normal
    assembly (``_assemble_normal`` — the same system ``_solve_one``
    factors, so ``rdr`` here EQUALS its chi2 output):

        A  = U^T D^{-1} U          (m, m)
        x  = U^T D^{-1} r          (m,)
        rdr = r^T D^{-1} r
        ld  = logdet D  (improper-prior constant dropped)

    with D^{-1} applied through the Woodbury identity on the
    preconditioned Cholesky of Sigma. The logdet undoes the column
    scaling explicitly: Sigma was assembled over M/(colmax*norm), so
    logdet Sigma_true = logdet Sigma_scaled
    + 2 sum_j pvalid_j log(colmax_j norm_j). Fully-padded batch slots
    (valid = pvalid = 0, unit nvec/phi, zero U) return exact zeros
    everywhere — safe to sum before slicing."""
    import jax
    import jax.numpy as jnp

    Sigma, b, w, colmax, norm = _assemble_normal(
        M, F, phi, r, nvec, valid, pvalid)
    q = F.shape[1]
    d = jnp.sqrt(jnp.diagonal(Sigma))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    cf = jax.scipy.linalg.cho_factor(Sigma / jnp.outer(d, d),
                                     lower=True)
    Mn = (M * pvalid[None, :]) / colmax[None, :] / norm[None, :]
    big = jnp.concatenate([Mn, F], axis=1)
    colvalid = jnp.concatenate([pvalid, jnp.ones(q)])
    Uw = U * w[:, None]
    V = (big.T @ Uw) * colvalid[:, None]
    u = Uw.T @ r
    G = U.T @ Uw
    SinvV = jax.scipy.linalg.cho_solve(cf, V / d[:, None]) \
        / d[:, None]
    A = G - V.T @ SinvV
    x = u - SinvV.T @ b
    xhat = jax.scipy.linalg.cho_solve(cf, b / d) / d
    rdr = jnp.sum(r * r * w) - xhat @ b
    ldSigma = 2.0 * jnp.sum(jnp.log(d)) + \
        2.0 * jnp.sum(jnp.log(jnp.diagonal(cf[0])))
    ld = jnp.sum(valid * jnp.log(nvec)) + jnp.sum(jnp.log(phi)) + \
        ldSigma + 2.0 * jnp.sum(pvalid * jnp.log(colmax * norm))
    return A, x, rdr, ld


def _gwb_block_batch(M, F, phi, r, nvec, valid, pvalid, U):
    """Leading-axis batch of ``_gwb_block_one`` — the kernel
    ``compile_with_plan`` shards over the pulsar axis."""
    import jax

    return jax.vmap(_gwb_block_one)(M, F, phi, r, nvec, valid,
                                    pvalid, U)


# ranks of the block kernel's inputs/outputs (for the sharding plan)
_BLOCK_NDIMS_IN = (3, 3, 2, 2, 2, 2, 2, 3)
_BLOCK_NDIMS_OUT = (3, 2, 1, 1)


def _gwb_block_one_np(M, F, phi, r, nvec, valid, pvalid, U):
    """Numpy mirror of ``_gwb_block_one`` (identical masked algebra,
    scipy Cholesky) — the host-failover path and the oracle's inner
    stage."""
    from scipy.linalg import cho_factor, cho_solve

    p = M.shape[1]
    q = F.shape[1]
    w = valid / nvec
    Mm = M * pvalid[None, :]
    colmax = np.max(np.abs(Mm), axis=0)
    colmax = np.where(colmax == 0, 1.0, colmax)
    Ms = Mm / colmax[None, :]
    norm = np.sqrt(np.sum(Ms * Ms * w[:, None], axis=0))
    norm = np.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    big = np.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw
    prior = np.concatenate([np.zeros(p), 1.0 / phi])
    Sigma = Sigma + np.diag(prior)
    colvalid = np.concatenate([pvalid, np.ones(q)])
    Sigma = Sigma * np.outer(colvalid, colvalid) + \
        np.diag(1.0 - colvalid)
    b = bigw.T @ r * colvalid
    d = np.sqrt(np.diagonal(Sigma)).copy()
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    cf = cho_factor(Sigma / np.outer(d, d), lower=True)
    Uw = U * w[:, None]
    V = (big.T @ Uw) * colvalid[:, None]
    u = Uw.T @ r
    G = U.T @ Uw
    SinvV = cho_solve(cf, V / d[:, None]) / d[:, None]
    A = G - V.T @ SinvV
    x = u - SinvV.T @ b
    xhat = cho_solve(cf, b / d) / d
    rdr = float(np.sum(r * r * w) - xhat @ b)
    ldSigma = 2.0 * float(np.sum(np.log(d))) + \
        2.0 * float(np.sum(np.log(np.diagonal(cf[0]))))
    ld = float(np.sum(valid * np.log(nvec)) + np.sum(np.log(phi)) +
               ldSigma + 2.0 * np.sum(pvalid *
                                      np.log(colmax * norm)))
    return A, x, rdr, ld


def gwb_blocks_np(stacked: dict, U: np.ndarray):
    """Batched numpy inner stage: (A (P,m,m), x (P,m), rdr (P,),
    ld (P,))."""
    P = stacked["M"].shape[0]
    outs = [_gwb_block_one_np(stacked["M"][k], stacked["F"][k],
                              stacked["phi"][k], stacked["r"][k],
                              stacked["nvec"][k],
                              stacked["valid"][k],
                              stacked["pvalid"][k], U[k])
            for k in range(P)]
    return (np.stack([o[0] for o in outs]),
            np.stack([o[1] for o in outs]),
            np.asarray([o[2] for o in outs]),
            np.asarray([o[3] for o in outs]))


# -- outer stage: cross-correlated Schur system ------------------------

def _gwb_outer_batch(A, x, rdr_sum, ld_sum, Gamma, fcols, tspan,
                     log10A, gamma):
    """log L at each (log10A[k], gamma[k]) grid point from the
    assembled blocks: factor Gamma once, then per point build and
    factor the (P*m)^2 second-stage Schur system
    S = Gamma^{-1} ⊗ diag(1/phi_g) + blockdiag(A). ``lax.map`` (not
    vmap) keeps one S in memory at a time — the chunk exists for
    failover granularity, not vectorization. The phi_g formula is
    the in-trace mirror of ``models.noise.powerlaw`` (times
    df = 1/Tspan)."""
    import jax
    import jax.numpy as jnp

    P, m = x.shape
    cfG = jax.scipy.linalg.cho_factor(Gamma, lower=True)
    Ginv = jax.scipy.linalg.cho_solve(cfG, jnp.eye(P))
    ldG = 2.0 * jnp.sum(jnp.log(jnp.diagonal(cfG[0])))
    xs = x.reshape(P * m)
    iP = jnp.arange(P)

    def one(point):
        la, ga = point
        phi_g = (10.0 ** la) ** 2 / (12.0 * jnp.pi ** 2) * \
            FYR ** (ga - 3.0) * fcols ** (-ga) / tspan
        S = jnp.kron(Ginv, jnp.diag(1.0 / phi_g))
        S = S.reshape(P, m, P, m).at[iP, :, iP, :].add(A) \
            .reshape(P * m, P * m)
        d = jnp.sqrt(jnp.diagonal(S))
        d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
        cf = jax.scipy.linalg.cho_factor(S / jnp.outer(d, d),
                                         lower=True)
        quad = (xs / d) @ jax.scipy.linalg.cho_solve(cf, xs / d)
        ldS = 2.0 * jnp.sum(jnp.log(d)) + \
            2.0 * jnp.sum(jnp.log(jnp.diagonal(cf[0])))
        return -0.5 * (rdr_sum - quad + ld_sum + m * ldG +
                       P * jnp.sum(jnp.log(phi_g)) + ldS)

    return jax.lax.map(one, (log10A, gamma))


_OUTER_NDIMS_IN = (3, 2, 0, 0, 2, 1, 0, 1, 1)
_OUTER_NDIMS_OUT = (1,)


def _gwb_outer_np(A, x, rdr_sum, ld_sum, Gamma, fcols, tspan,
                  log10A, gamma):
    """Numpy mirror of ``_gwb_outer_batch`` — CPU oracle outer stage
    and the sweep chunks' host-failover target."""
    from scipy.linalg import cho_factor, cho_solve

    P, m = x.shape
    cfG = cho_factor(Gamma, lower=True)
    Ginv = cho_solve(cfG, np.eye(P))
    ldG = 2.0 * float(np.sum(np.log(np.diagonal(cfG[0]))))
    xs = x.reshape(P * m)
    out = np.zeros(len(log10A))
    for k, (la, ga) in enumerate(zip(log10A, gamma)):
        phi_g = powerlaw(fcols, 10.0 ** float(la), float(ga)) \
            / float(tspan)
        S = np.kron(Ginv, np.diag(1.0 / phi_g))
        S4 = S.reshape(P, m, P, m)
        for a in range(P):
            S4[a, :, a, :] += A[a]
        S = S4.reshape(P * m, P * m)
        d = np.sqrt(np.diagonal(S)).copy()
        d[(d == 0) | ~np.isfinite(d)] = 1.0
        cf = cho_factor(S / np.outer(d, d), lower=True)
        quad = float((xs / d) @ cho_solve(cf, xs / d))
        ldS = 2.0 * float(np.sum(np.log(d))) + \
            2.0 * float(np.sum(np.log(np.diagonal(cf[0]))))
        out[k] = -0.5 * (rdr_sum - quad + ld_sum + m * ldG +
                         P * float(np.sum(np.log(phi_g))) + ldS)
    return out


def gwb_loglik_np(stacked: dict, U: np.ndarray, Gamma: np.ndarray,
                  fcols: np.ndarray, tspan: float,
                  log10A: np.ndarray, gamma: np.ndarray):
    """Full numpy mirror: inner blocks + cross-correlated outer
    stage, end to end on the host — the CPU oracle for the device
    path (tests/test_gwb.py) and the mirror ``GWBLikelihood`` falls
    over to."""
    A, x, rdr, ld = gwb_blocks_np(stacked, U)
    return _gwb_outer_np(A, x, float(rdr.sum()), float(ld.sum()),
                         np.asarray(Gamma), np.asarray(fcols),
                         float(tspan), np.asarray(log10A),
                         np.asarray(gamma))


# -- the likelihood object ---------------------------------------------

class GWBLikelihood:
    """Array-level GWB marginal likelihood over fixed per-pulsar
    linearized problems.

    Blocks are assembled ONCE (sharded over ``mesh``'s pulsar axis
    when given — the hyperparameters never touch the inner stage),
    then ``loglik_grid`` sweeps (log10_A, gamma) points through
    chunked supervised dispatches of the outer Schur system. All
    device calls ride the dispatch supervisor with the numpy mirror
    as labeled host failover."""

    def __init__(self, pairs: Optional[Sequence] = None,
                 problems: Optional[Sequence[PulsarProblem]] = None,
                 positions: Optional[np.ndarray] = None,
                 gamma_matrix: Optional[np.ndarray] = None,
                 nfreq: int = 10, mesh=None, axis: str = "pulsar",
                 metrics: Optional[PTAMetrics] = None,
                 supervisor=None, track_mode=None):
        if problems is None:
            if pairs is None:
                raise ValueError("need pairs or problems")
            problems = [build_problem(t, m, track_mode=track_mode)
                        for t, m in pairs]
        self.problems = list(problems)
        P = len(self.problems)
        if P < 2:
            raise ValueError("a pulsar-ARRAY likelihood needs >= 2 "
                             "pulsars")
        if gamma_matrix is None:
            if positions is None:
                models = [pr.model for pr in self.problems]
                if any(m is None for m in models):
                    raise ValueError(
                        "problems carry no models: pass positions= "
                        "or gamma_matrix=")
                positions = pulsar_positions(models)
            gamma_matrix = hd_matrix(positions)
        self.Gamma = np.asarray(gamma_matrix, dtype=np.float64)
        if self.Gamma.shape != (P, P):
            raise ValueError(
                f"gamma_matrix shape {self.Gamma.shape} != ({P},{P})")
        toas_list = [pr.toas for pr in self.problems]
        if any(t is None for t in toas_list):
            raise ValueError("problems carry no TOAs (build them via "
                             "build_problem) — the common-span GWB "
                             "basis needs the TOA epochs")
        U_list, self.fcols, self.tspan = gwb_basis(toas_list,
                                                   int(nfreq))
        self.nfreq = int(nfreq)
        self.m = 2 * self.nfreq
        self.stacked = stack_problems(self.problems)
        N = self.stacked["M"].shape[1]
        self.U = np.zeros((P, N, self.m))
        for k, Uk in enumerate(U_list):
            self.U[k, :Uk.shape[0], :] = Uk
        self.mesh = mesh
        self.axis = axis
        self.metrics = metrics if metrics is not None else \
            PTAMetrics()
        self._supervisor = supervisor
        self._blocks = None
        self.blocks_info: dict = {}

    @property
    def npulsars(self) -> int:
        return len(self.problems)

    def _sup(self):
        if self._supervisor is not None:
            return self._supervisor
        from pint_tpu.runtime import get_supervisor

        return get_supervisor()

    def build_blocks(self, pool: str = "device", force: bool = False):
        """Assemble (A, x, rdr_sum, ld_sum) in ONE supervised batch
        dispatch, sharded over the pulsar axis when a mesh was given
        (``compile_with_plan`` — per-device blocks, zero
        collectives). Cached: the GWB hyperparameters never reach
        this stage. ``blocks_info['used_pool']`` labels who actually
        served."""
        if self._blocks is not None and not force:
            return self._blocks
        from pint_tpu import obs

        P = self.npulsars
        arrs = dict(self.stacked)
        arrs["U"] = self.U
        arrs = pad_batch(arrs, self.mesh, self.axis)
        names = ("M", "F", "phi", "r", "nvec", "valid", "pvalid",
                 "U")
        kernel = compile_with_plan(
            _gwb_block_batch, name="pta.gwb_blocks",
            ndims_in=_BLOCK_NDIMS_IN, ndims_out=_BLOCK_NDIMS_OUT,
            mesh=self.mesh, axis=self.axis)
        mesh, axis = self.mesh, self.axis
        fell_over = []
        info = self.blocks_info = {}

        def run():
            import jax
            import jax.numpy as jnp

            if mesh is not None:
                st = {k: jax.device_put(
                    v, batch_sharding(mesh, axis, v.ndim))
                    for k, v in arrs.items()}
            else:
                st = {k: jnp.asarray(v) for k, v in arrs.items()}
            out = kernel(*(st[n] for n in names))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return tuple(np.asarray(o)[:P] for o in out)

        def host():
            out = gwb_blocks_np(self.stacked, self.U)
            return tuple(np.asarray(o)[:P] for o in out)

        with obs.span("pta.gwb_blocks", npulsars=P, m=self.m,
                      sharded=mesh is not None):
            if pool == "host":
                A, x, rdr, ld = self._sup().dispatch(
                    host, key="pta.gwb_blocks", pinned=True)
                info["used_pool"] = "host"
            else:
                def host_counted():
                    fell_over.append(True)
                    return host()

                A, x, rdr, ld = self._sup().dispatch(
                    run, key="pta.gwb_blocks",
                    fallback=host_counted)
                info["used_pool"] = "host-failover" if fell_over \
                    else "device"
        self.metrics.bump("block_assemblies")
        self._blocks = (np.asarray(A), np.asarray(x),
                        float(np.sum(rdr)), float(np.sum(ld)))
        return self._blocks

    def loglik_grid(self, log10A, gamma, chunk: Optional[int] = None,
                    pool: str = "device", sync: bool = True,
                    info: Optional[dict] = None, progress=None,
                    key_tag: str = "pta.gwb"):
        """log L at each grid point, swept in chunks of
        ``config.gwb_chunk()`` supervised dispatches (chunk boundary
        = failover/deadline boundary). ``sync=False`` returns a
        zero-arg collect (the serve path's lazy half)."""
        from pint_tpu import config

        K = int(chunk) if chunk else config.gwb_chunk()
        collect = gwb_sweep_driver(
            self, np.asarray(log10A, dtype=np.float64).ravel(),
            np.asarray(gamma, dtype=np.float64).ravel(), K,
            supervisor=self._sup(), key_tag=key_tag, pool=pool,
            sync=sync, info=info, progress=progress)
        if sync:
            return collect()
        return collect

    def loglik(self, log10_A: float, gamma: float,
               **kw) -> float:
        """Single-point log L (a grid of one)."""
        return float(self.loglik_grid([log10_A], [gamma], **kw)[0])


def gwb_sweep_driver(like: GWBLikelihood, log10A: np.ndarray,
                     gamma: np.ndarray, K: int, supervisor=None,
                     key_tag: str = "pta.gwb",
                     pool: str = "device", sync: bool = True,
                     info: Optional[dict] = None, progress=None):
    """Chunked supervised sweep of the outer Schur system — the
    template ``posterior_chunk_driver`` set: each chunk of K grid
    points is its own deadline-bounded dispatch with the numpy outer
    mirror as host failover (the blocks are already collected host
    arrays, so a mid-sweep device death finishes on the host from
    the chunk boundary), per-chunk ``progress`` acks, and
    ``info['used_pool']`` labeling. The last chunk pads by repeating
    its final point (dropped on gather). ``sync=False`` pipelines
    chunk 0 on the supervisor's async path."""
    from pint_tpu import obs

    if supervisor is None:
        supervisor = like._sup()
    if info is None:
        info = {}
    npts = len(log10A)
    if npts == 0:
        def empty():
            info["used_pool"] = pool if pool == "host" else "device"
            return np.zeros(0)
        return empty
    nchunks = -(-npts // K)
    A, x, rdr_sum, ld_sum = like.build_blocks(pool=pool)
    if like.blocks_info.get("used_pool") == "host-failover":
        info["used_pool"] = "host-failover"
    Gamma, fcols, tspan = like.Gamma, like.fcols, like.tspan
    kernel = compile_with_plan(
        _gwb_outer_batch, name="pta.gwb_sweep",
        ndims_in=_OUTER_NDIMS_IN, ndims_out=_OUTER_NDIMS_OUT)
    fell_over: List[bool] = []
    placed: dict = {}

    def _chunk_grids(c):
        la = np.full(K, log10A[npts - 1])
        ga = np.full(K, gamma[npts - 1])
        n = min(npts, (c + 1) * K) - c * K
        la[:n] = log10A[c * K:c * K + n]
        ga[:n] = gamma[c * K:c * K + n]
        return la, ga, n

    def _chunk_closures(c):
        la, ga, n = _chunk_grids(c)

        def run():
            import jax.numpy as jnp

            if not placed:
                placed.update(
                    A=jnp.asarray(A), x=jnp.asarray(x),
                    G=jnp.asarray(Gamma), f=jnp.asarray(fcols))
            out = kernel(placed["A"], placed["x"], jnp.asarray(rdr_sum), jnp.asarray(ld_sum), placed["G"], placed["f"], jnp.asarray(tspan), jnp.asarray(la), jnp.asarray(ga))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            h = np.asarray(out)
            return h if h.flags.owndata else h.copy()

        def run_pinned():
            placed.clear()
            return _gwb_outer_np(A, x, rdr_sum, ld_sum, Gamma,
                                 fcols, tspan, la, ga)

        return run, run_pinned, n

    def chunk_run(c):
        run, run_pinned, n = _chunk_closures(c)
        with obs.span("pta.gwb_sweep", chunk=c, points=K,
                      pool=pool):
            if pool == "host":
                out = supervisor.dispatch(
                    run_pinned, key=f"{key_tag}/chunk{c}", steps=K,
                    pinned=True)
                info["used_pool"] = "host"
            else:
                def host_counted():
                    fell_over.append(True)
                    return run_pinned()

                out = supervisor.dispatch(
                    run, key=f"{key_tag}/chunk{c}", steps=K,
                    fallback=host_counted)
        like.metrics.bump("gwb_solves")
        like.metrics.bump("hd_outer_solves", K)
        return out, n

    def _finish(vals):
        if pool != "host" and \
                info.get("used_pool") != "host-failover":
            info["used_pool"] = "host-failover" if fell_over \
                else "device"
        return np.concatenate(vals)[:npts]

    def run_chunks():
        vals = []
        for c in range(nchunks):
            out, _ = chunk_run(c)
            vals.append(np.asarray(out))
            if progress is not None:
                progress(min(npts, (c + 1) * K))
        return _finish(vals)

    if sync:
        return run_chunks
    first_fut = None
    if pool != "host":
        run0, run0_pinned, _ = _chunk_closures(0)

        def host_counted0():
            fell_over.append(True)
            return run0_pinned()

        with obs.span("pta.gwb_sweep.issue", chunk=0, points=K):
            first_fut = supervisor.dispatch_async(
                run0, key=f"{key_tag}/chunk0", steps=K,
                fallback=host_counted0)

    def collect():
        nonlocal first_fut
        if first_fut is None:
            return run_chunks()
        out0 = first_fut.result()
        first_fut = None
        like.metrics.bump("gwb_solves")
        like.metrics.bump("hd_outer_solves", K)
        vals = [np.asarray(out0)]
        if progress is not None:
            progress(min(npts, K))
        for c in range(1, nchunks):
            out, _ = chunk_run(c)
            vals.append(np.asarray(out))
            if progress is not None:
                progress(min(npts, (c + 1) * K))
        return _finish(vals)

    return collect
