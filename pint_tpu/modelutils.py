"""Model transformation helpers: ecliptic <-> equatorial astrometry.

Reference: src/pint/modelutils.py (model_equatorial_to_ecliptic,
model_ecliptic_to_equatorial). Positions rotate through the IAU
obliquity matrix; proper motions rotate with the local tangent-plane
Jacobian (position-angle rotation); PX/POSEPOCH carry over.
"""

from __future__ import annotations

import copy

import numpy as np

from pint_tpu.models.astrometry import (
    AstrometryEcliptic,
    AstrometryEquatorial,
    icrs_to_ecliptic_matrix,
)

__all__ = ["model_ecliptic_to_equatorial",
           "model_equatorial_to_ecliptic"]


def _unit(lon, lat):
    return np.array([np.cos(lat) * np.cos(lon),
                     np.cos(lat) * np.sin(lon), np.sin(lat)])


def _lonlat(v):
    return float(np.arctan2(v[1], v[0]) % (2 * np.pi)), \
        float(np.arcsin(np.clip(v[2], -1, 1)))


def _basis(lon, lat):
    """(east, north) unit vectors at (lon, lat)."""
    e = np.array([-np.sin(lon), np.cos(lon), 0.0])
    n = np.array([-np.sin(lat) * np.cos(lon),
                  -np.sin(lat) * np.sin(lon), np.cos(lat)])
    return e, n


def _convert(model, to_ecliptic: bool, ecl: str = "IERS2010"):
    src_name = "AstrometryEquatorial" if to_ecliptic else \
        "AstrometryEcliptic"
    src = model.components.get(src_name)
    if src is None:
        raise ValueError(f"model has no {src_name}")
    if to_ecliptic:
        obl = AstrometryEcliptic.obliquity_arcsec(ecl)
        M = icrs_to_ecliptic_matrix(obl)  # ecliptic <- ICRS
        lon0, lat0 = src.RAJ.value, src.DECJ.value
        pml, pmb = src.PMRA.value or 0.0, src.PMDEC.value or 0.0
        dst = AstrometryEcliptic()
        dst.ECL.value = ecl
        out_names = ("ELONG", "ELAT", "PMELONG", "PMELAT")
    else:
        M = np.asarray(src._ecl_matrix())  # ICRS <- ecliptic
        lon0, lat0 = src.ELONG.value, src.ELAT.value
        pml, pmb = src.PMELONG.value or 0.0, src.PMELAT.value or 0.0
        dst = AstrometryEquatorial()
        out_names = ("RAJ", "DECJ", "PMRA", "PMDEC")

    v = M @ _unit(lon0, lat0)
    lon1, lat1 = _lonlat(v)
    # rotate the proper-motion vector: express (pm_east, pm_north) in
    # the source basis as a 3-vector, rotate, project on the dest basis
    e0, n0 = _basis(lon0, lat0)
    pm_vec = M @ (pml * e0 + pmb * n0)
    e1, n1 = _basis(lon1, lat1)
    pm_lon, pm_lat = float(pm_vec @ e1), float(pm_vec @ n1)

    new = copy.deepcopy(model)
    new.remove_component(src_name)
    new.add_component(dst, setup=False)
    vals = (lon1, lat1, pm_lon, pm_lat)
    for nm, val in zip(out_names, vals):
        dst.params[nm].value = val
    # rotate the on-sky error ellipse (diagonal approximation): the
    # east/north variances mix through the same position-angle rotation
    # as the PM vector; longitude errors carry 1/cos(lat) coordinate
    # factors (east = d(lon) cos(lat))
    in_names = ("RAJ", "DECJ", "PMRA", "PMDEC") if to_ecliptic else \
        ("ELONG", "ELAT", "PMELONG", "PMELAT")
    c_rot = float((M @ e0) @ e1)
    s_rot = float((M @ e0) @ n1)
    sig_lon0 = src.params[in_names[0]].uncertainty
    sig_lat0 = src.params[in_names[1]].uncertainty
    if sig_lon0 is not None and sig_lat0 is not None:
        ve0 = (sig_lon0 * np.cos(lat0)) ** 2
        vn0 = sig_lat0 ** 2
        ve1 = c_rot ** 2 * ve0 + s_rot ** 2 * vn0
        vn1 = s_rot ** 2 * ve0 + c_rot ** 2 * vn0
        dst.params[out_names[0]].uncertainty = float(
            np.sqrt(ve1) / np.cos(lat1))
        dst.params[out_names[1]].uncertainty = float(np.sqrt(vn1))
    spm_lon = src.params[in_names[2]].uncertainty
    spm_lat = src.params[in_names[3]].uncertainty
    if spm_lon is not None and spm_lat is not None:
        # PM components are already on-sky (mu_lon* includes cos lat)
        ve1 = c_rot ** 2 * spm_lon ** 2 + s_rot ** 2 * spm_lat ** 2
        vn1 = s_rot ** 2 * spm_lon ** 2 + c_rot ** 2 * spm_lat ** 2
        dst.params[out_names[2]].uncertainty = float(np.sqrt(ve1))
        dst.params[out_names[3]].uncertainty = float(np.sqrt(vn1))
    for nm_src, nm_dst in zip(in_names, out_names):
        sp = src.params[nm_src]
        dst.params[nm_dst].frozen = sp.frozen
    for shared in ("PX", "POSEPOCH", "PMRV"):
        if shared in src.params and shared in dst.params:
            sp, dp = src.params[shared], dst.params[shared]
            dp.value, dp.frozen = sp.value, sp.frozen
            dp.uncertainty = sp.uncertainty
    dst.setup()
    dst.validate()
    new.invalidate_cache()
    return new


def model_equatorial_to_ecliptic(model, ecl: str = "IERS2010"):
    """RAJ/DECJ model -> ELONG/ELAT model (reference:
    modelutils.model_equatorial_to_ecliptic). ``ecl`` picks the
    obliquity convention (the new model's ECL parameter)."""
    return _convert(model, to_ecliptic=True, ecl=ecl)


def model_ecliptic_to_equatorial(model):
    """ELONG/ELAT model -> RAJ/DECJ model (reference:
    modelutils.model_ecliptic_to_equatorial)."""
    return _convert(model, to_ecliptic=False)
