"""Labeled matrix abstractions over the fitter linear algebra.

Reference: src/pint/pint_matrix.py (PintMatrix, DesignMatrix,
CovarianceMatrix, DesignMatrixMaker, combine_design_matrices_by_
quantity/param). The jitted kernels consume plain arrays; these
wrappers carry the (parameter, unit) labels for display, wideband
stacking, and correlation-matrix reporting.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PintMatrix", "DesignMatrix", "CovarianceMatrix",
           "combine_design_matrices_by_quantity",
           "combine_design_matrices_by_param"]


class PintMatrix:
    """A 2-D array with labeled columns (reference: PintMatrix; the
    row axis is the TOA/measurement index)."""

    def __init__(self, matrix, labels: Sequence[str],
                 units: Optional[Sequence[str]] = None,
                 quantity: str = "toa"):
        self.matrix = np.asarray(matrix)
        self.labels = list(labels)
        self.units = list(units) if units is not None else \
            [""] * len(self.labels)
        self.quantity = quantity
        if self.matrix.ndim != 2 or \
                self.matrix.shape[1] != len(self.labels):
            raise ValueError("matrix/labels shape mismatch: "
                             f"{self.matrix.shape} vs "
                             f"{len(self.labels)} labels")
        if len(self.units) != len(self.labels):
            raise ValueError("units/labels length mismatch: "
                             f"{len(self.units)} vs {len(self.labels)}")

    @property
    def shape(self):
        return self.matrix.shape

    def get_label_index(self, label: str) -> int:
        return self.labels.index(label)

    def get_column(self, label: str) -> np.ndarray:
        return self.matrix[:, self.get_label_index(label)]

    def __repr__(self):
        return (f"<{type(self).__name__} {self.matrix.shape} "
                f"labels={self.labels}>")


class DesignMatrix(PintMatrix):
    """d(residual)/d(param) with units s/param-unit (reference:
    DesignMatrix + DesignMatrixMaker)."""

    @classmethod
    def from_model(cls, model, toas, incoffset: bool = True,
                   quantity: str = "toa") -> "DesignMatrix":
        M, names, units = model.designmatrix(toas, incoffset=incoffset)
        return cls(np.asarray(M), names, units, quantity=quantity)

    def derivative_params(self) -> List[str]:
        return [p for p in self.labels if p != "Offset"]


class CovarianceMatrix(PintMatrix):
    """Symmetric parameter covariance with labels on both axes
    (reference: CovarianceMatrix)."""

    @classmethod
    def from_fitter(cls, fitter) -> "CovarianceMatrix":
        cov = fitter.parameter_covariance_matrix
        if cov is None:
            raise ValueError("fit first: no covariance available")
        names = ["Offset"] + list(fitter.model.free_params)
        return cls(np.asarray(cov), names)

    def to_correlation(self) -> "CovarianceMatrix":
        d = np.sqrt(np.diag(self.matrix))
        d[d == 0] = 1.0
        return CovarianceMatrix(self.matrix / np.outer(d, d),
                                self.labels, self.units)

    def prettyprint(self, prec: int = 3) -> str:
        """Lower-triangular correlation table (reference:
        CovarianceMatrix.prettyprint)."""
        corr = self.to_correlation().matrix
        w = max(8, prec + 5)
        lines = [" " * 10 + "".join(f"{nm[:w]:>{w + 1}}"
                                    for nm in self.labels)]
        for i, nm in enumerate(self.labels):
            row = "".join(f"{corr[i, j]:>{w + 1}.{prec}f}"
                          for j in range(i + 1))
            lines.append(f"{nm[:10]:<10}{row}")
        return "\n".join(lines)


def combine_design_matrices_by_quantity(matrices) -> DesignMatrix:
    """Stack row-blocks of different measured quantities (e.g. [TOA;
    DM] for wideband) sharing the same parameter columns (reference:
    combine_design_matrices_by_quantity)."""
    first = matrices[0]
    for m in matrices[1:]:
        if m.labels != first.labels:
            raise ValueError("parameter columns differ: "
                             f"{m.labels} vs {first.labels}")
        if m.units != first.units:
            raise ValueError("parameter column units differ: "
                             f"{m.units} vs {first.units}")
    return DesignMatrix(
        np.concatenate([m.matrix for m in matrices], axis=0),
        first.labels, first.units,
        quantity="+".join(m.quantity for m in matrices))


def combine_design_matrices_by_param(matrices) -> DesignMatrix:
    """Concatenate parameter columns for the same measurement rows
    (reference: combine_design_matrices_by_param)."""
    first = matrices[0]
    for m in matrices[1:]:
        if m.matrix.shape[0] != first.matrix.shape[0]:
            raise ValueError("row counts differ")
    labels: List[str] = []
    units: List[str] = []
    for m in matrices:
        for nm, u in zip(m.labels, m.units):
            if nm in labels:
                raise ValueError(f"duplicate column {nm!r}")
            labels.append(nm)
            units.append(u)
    return DesignMatrix(
        np.concatenate([m.matrix for m in matrices], axis=1),
        labels, units, quantity=first.quantity)
