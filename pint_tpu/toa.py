"""TOA container and the ingestion pipeline (clock → TDB → posvels).

Reference: src/pint/toa.py (TOA, TOAs, get_TOAs). Architectural change
for TPU (SURVEY.md §3.1 boundary note): all Earth-frame, clock, and
ephemeris physics is precomputed **once, on the host** into flat numpy
columns; the device then sees a closed struct-of-arrays pytree
(``ToaBatch``) of jnp arrays. Everything downstream of ``to_batch()`` is
pure array math under jit.

Times are carried as (int day f64, fraction as host double-double pair)
and never squeezed through a single float64.
"""

from __future__ import annotations

import itertools
import os
import warnings
from typing import Dict, List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from pint_tpu import c_m_s, config
from pint_tpu.ephemeris import get_ephemeris
from pint_tpu.io.tim import TimTOA, parse_tim, write_tim
from pint_tpu.observatory import get_observatory
from pint_tpu.ops import dd_np
from pint_tpu.ops.dd import DD
from pint_tpu.time import mjd as mjdmod
from pint_tpu.time import scales


def _env_dir_key(d) -> Optional[str]:
    """Stringify a config dir (Optional[Path]) for the TOA-cache
    digest — None stays None so an unset override keys identically
    across platforms."""
    return None if d is None else str(d)

SECS_PER_DAY = 86400.0

# Monotonic token identifying a TOAs *state* (object identity is not
# enough: Python reuses ids after GC, and a TOAs can be mutated in
# place by the pipeline). TimingModel keys its per-batch cache on this.
_TOAS_SERIAL = itertools.count(1)

# Planets used by PLANET_SHAPIRO, in reference order
# (src/pint/models/solar_system_shapiro.py _ss_obj_delay callers).
PLANETS = ("jupiter", "saturn", "venus", "uranus", "neptune")


class ToaBatch(NamedTuple):
    """Device-side struct-of-arrays view of a TOA set. All leaves are jnp
    arrays; shapes are static per jit cache key. Positions are in
    light-seconds, velocities in lt-s/s (i.e. v/c), matching the natural
    units of delay formulas.
    """

    tdb_day: jnp.ndarray        # (N,) integer TDB day (f64-exact)
    tdb_frac: DD                # (N,) dd TDB day fraction
    freq_mhz: jnp.ndarray       # (N,) barycentric obs frequency (inf ok)
    error_us: jnp.ndarray       # (N,) raw TOA uncertainty
    ssb_obs_pos: jnp.ndarray    # (N,3) SSB→observatory, lt-s
    ssb_obs_vel: jnp.ndarray    # (N,3) d/dt of the above, lt-s/s
    obs_sun_pos: jnp.ndarray    # (N,3) observatory→Sun, lt-s
    obs_planet_pos: jnp.ndarray  # (P,N,3) observatory→planet, lt-s
    pulse_number: jnp.ndarray   # (N,) f64, NaN where untracked

    # unit metadata per leaf (pint_tpu.units strings) — the batch half
    # of the build-time unit discipline; component authors consult this
    # the way parameter slots consult Component.param_dimensions
    UNITS = {
        "tdb_day": "d", "tdb_frac": "d", "freq_mhz": "MHz",
        "error_us": "us", "ssb_obs_pos": "ls", "ssb_obs_vel": "ls/s",
        "obs_sun_pos": "ls", "obs_planet_pos": "ls",
        "pulse_number": "turn",
    }

    @property
    def ntoas(self):
        return self.freq_mhz.shape[0]


class TOAs:
    """Host-side TOA table (reference: TOAs over an astropy Table; here a
    plain struct of numpy columns + python-side flags)."""

    def __init__(self, timtoas: List[TimTOA]):
        days, frac = mjdmod.parse_mjd_strings([t.mjd_str for t in timtoas])
        self.mjd_day = days                      # UTC (pulsar-MJD) int day
        self.mjd_frac = frac                     # dd day fraction
        self.freq_mhz = np.array(
            [t.freq_mhz if t.freq_mhz > 0 else np.inf for t in timtoas])
        self.error_us = np.array([t.error_us for t in timtoas])
        self.obs = [get_observatory(t.obs).name for t in timtoas]
        self.flags: List[Dict[str, str]] = [dict(t.flags) for t in timtoas]
        self.names = [t.name for t in timtoas]
        # applied "TIME" offsets from the tim file (seconds)
        toff = np.array([float(f.get("to", 0.0)) for f in self.flags])
        if np.any(toff != 0.0):
            self.mjd_frac = dd_np.add(
                self.mjd_frac, dd_np.div_f(dd_np.dd(toff), SECS_PER_DAY))
        self.clock_applied = False
        # populated by the pipeline:
        self.tdb_day: Optional[np.ndarray] = None
        self.tdb_frac = None
        self.ssb_obs_pos = None   # (N,3) meters
        self.ssb_obs_vel = None   # (N,3) m/s
        self.obs_sun_pos = None
        self.obs_planet_pos = None  # dict name -> (N,3) m
        self.ephem = None
        self.planets = False
        self._serial = next(_TOAS_SERIAL)

    def _touch(self):
        """Mark this TOAs state as changed (invalidates model caches)."""
        self._serial = next(_TOAS_SERIAL)

    def __setstate__(self, d):
        """A pickled serial is only unique in the ORIGIN process: an
        unpickled TOAs carrying it could collide with a locally
        created TOAs in the receiving process and make
        TimingModel.get_cache return the wrong cached masks/TZR batch
        silently — reassign a fresh process-local serial on load."""
        self.__dict__.update(d)
        self._serial = next(_TOAS_SERIAL)

    @property
    def cache_key(self):
        return self._serial

    # ---------------- basic container protocol ----------------

    def __len__(self):
        return len(self.obs)

    @property
    def ntoas(self):
        return len(self.obs)

    def get_mjds(self, high_precision=False):
        """UTC MJDs as f64 (or (day, frac-dd) when high_precision)."""
        if high_precision:
            return self.mjd_day, self.mjd_frac
        return self.mjd_day + dd_np.to_f64(self.mjd_frac)

    def get_errors(self):
        return self.error_us

    def get_freqs(self):
        return self.freq_mhz

    def get_obss(self):
        return list(self.obs)

    def get_flag_value(self, flag, fill_value=None, as_type=None):
        out = []
        for f in self.flags:
            v = f.get(flag, fill_value)
            if v is not None and as_type is not None:
                v = as_type(v)
            out.append(v)
        return out

    @property
    def index(self):
        """Original-position index of each TOA, surviving select()
        subsets (reference: the TOAs table "index" column). Lazily
        arange for containers built before the first access."""
        ix = getattr(self, "_index", None)
        if ix is None or len(ix) != self.ntoas:
            self._index = np.arange(self.ntoas)
        return self._index

    def renumber(self, index_order=True):
        """Reset the index column (reference: TOAs.renumber):
        index_order=True numbers 0..N-1 in current storage order;
        False preserves the relative order of the existing indices
        (rank-renumber after deletions)."""
        if index_order:
            self._index = np.arange(self.ntoas)
        else:
            self._index = np.argsort(np.argsort(self.index))
        self._touch()

    def get_pulse_numbers(self):
        pn = self.get_flag_value("pn", fill_value="nan", as_type=float)
        arr = np.array(pn)
        return None if np.all(np.isnan(arr)) else arr

    def compute_pulse_numbers(self, model):
        """Attach -pn flags from the model's nearest-integer phase
        (reference: TOAs.compute_pulse_numbers)."""
        ph = model.phase(self, abs_phase=True)
        pn = np.asarray(ph.int)
        for f, p in zip(self.flags, pn):
            f["pn"] = repr(float(p))
        self._touch()

    def select(self, mask):
        """Boolean-mask subset (new TOAs object; reference: TOAs.select
        but non-destructive)."""
        mask = np.asarray(mask)
        idx = np.flatnonzero(mask) if mask.dtype == bool else mask
        out = object.__new__(TOAs)
        out.mjd_day = self.mjd_day[idx]
        out.mjd_frac = (self.mjd_frac[0][idx], self.mjd_frac[1][idx])
        out.freq_mhz = self.freq_mhz[idx]
        out.error_us = self.error_us[idx]
        out.obs = [self.obs[i] for i in idx]
        out.flags = [dict(self.flags[i]) for i in idx]
        out.names = [self.names[i] for i in idx]
        out.clock_applied = self.clock_applied
        out.ephem = self.ephem
        out.planets = self.planets
        for col in ("tdb_day", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
            v = getattr(self, col)
            setattr(out, col, None if v is None else v[idx])
        out.tdb_frac = None if self.tdb_frac is None else \
            (self.tdb_frac[0][idx], self.tdb_frac[1][idx])
        out.obs_planet_pos = None if self.obs_planet_pos is None else \
            {k: v[idx] for k, v in self.obs_planet_pos.items()}
        out._index = self.index[idx]
        out._serial = next(_TOAS_SERIAL)
        return out

    def first_MJD(self):
        return float(np.min(self.get_mjds()))

    def last_MJD(self):
        return float(np.max(self.get_mjds()))

    # ---------------- the pipeline ----------------

    def apply_clock_corrections(self, include_gps=True, include_bipm=True,
                                bipm_version="BIPM2021", limits="warn"):
        """Add observatory clock chain to the raw MJDs, per obs group
        (reference: TOAs.apply_clock_corrections)."""
        if self.clock_applied:
            return
        mjd_f64 = self.get_mjds()
        corr = np.zeros(self.ntoas)
        for site in set(self.obs):
            m = np.array([o == site for o in self.obs])
            obs = get_observatory(site)
            corr[m] = obs.clock_corrections(
                mjd_f64[m], include_gps=include_gps,
                include_bipm=include_bipm, bipm_version=bipm_version,
                limits=limits)
        self.mjd_frac = dd_np.add(
            self.mjd_frac, dd_np.div_f(dd_np.dd(corr), SECS_PER_DAY))
        for f, c in zip(self.flags, corr):
            f["clkcorr"] = repr(float(c))
        self.clock_applied = True
        self._touch()

    def compute_TDBs(self, ephem=None):
        """UTC(site) → TT → TDB per TOA (reference: TOAs.compute_TDBs).
        Barycenter-site TOAs are already TDB and pass through.

        For ground sites the topocentric TDB−TT term
        +(v_earth . r_obs)/c^2 (Moyer; diurnal, amplitude ~2.1 us) is
        applied on top of the geocentric Fairhead–Bretagnon series —
        the reference gets the same term via location-aware astropy
        Time conversions."""
        tdb_day = np.array(self.mjd_day)
        fhi = np.array(self.mjd_frac[0])
        flo = np.array(self.mjd_frac[1])
        scale = np.array(
            [get_observatory(o).timescale for o in self.obs])
        utc_mask = scale != "tdb"
        if np.any(utc_mask):
            day = self.mjd_day[utc_mask]
            frac = (self.mjd_frac[0][utc_mask], self.mjd_frac[1][utc_mask])
            tt = scales.utc_mjd_to_tt_mjd(day, frac)
            tdb = scales.tt_mjd_to_tdb_mjd(tt)
            # topocentric term for every non-geocentric observer
            # (ground sites AND satellites: a LEO r_obs ~6.8e6 m gives
            # up to ~2.3 us); geocenter's zero position contributes 0
            tt_f64 = dd_np.to_f64(tt)
            utc_f64 = (day + frac[0] + frac[1])
            dt_topo = np.zeros_like(tt_f64)
            sub_obs = [o for o, m in zip(self.obs, utc_mask) if m]
            sub_flags = [f for f, m in zip(self.flags, utc_mask) if m]
            self._site_gcrs_cache = {}
            if sub_obs:
                eph = get_ephemeris(ephem)
                # earth velocity [m/s]; tt is within ~2 ms of tdb —
                # far below the velocity's variation scale
                _, v_earth = eph.ssb_posvel("earth", tt_f64)
                for site in set(sub_obs):
                    m = np.array([o == site for o in sub_obs])
                    obs = get_observatory(site)
                    if hasattr(obs, "posvel_from_flags"):
                        r_m, v_m = obs.posvel_from_flags(
                            [f for f, mm in zip(sub_flags, m) if mm])
                    else:
                        r_m, v_m = obs.gcrs_posvel(utc_f64[m],
                                                   tt_f64[m])
                    # reused by compute_posvels: the epoch difference
                    # (TT vs TDB in the slow precession argument) is
                    # ~2 ms * 1e-12 rad/s — far below any tolerance
                    self._site_gcrs_cache[site] = (m, r_m, v_m)
                    dt_topo[m] = np.sum(v_earth[m] * r_m,
                                        axis=-1) / c_m_s ** 2
            tdb = dd_np.add(tdb, dd_np.div_f(dd_np.dd(dt_topo),
                                             SECS_PER_DAY))
            # renormalize to (int day, frac) — keep day integral for exact
            # downstream (day − epoch) arithmetic
            d = np.round(tdb[0])
            rest = dd_np.add_f(dd_np.dd(tdb[0] - d, tdb[1]), 0.0)
            tdb_day[utc_mask] = d
            fhi[utc_mask] = rest[0]
            flo[utc_mask] = rest[1]
        self.tdb_day = tdb_day
        self._touch()
        self.tdb_frac = (fhi, flo)

    def compute_posvels(self, ephem=None, planets=False):
        """Observatory SSB position/velocity and Sun/planet geometry at
        each TDB (reference: TOAs.compute_posvels)."""
        if self.tdb_day is None:
            self.compute_TDBs(ephem=ephem)
        eph = get_ephemeris(ephem)
        self.ephem = getattr(eph, "name", str(ephem))
        self.planets = planets
        tdb = self.tdb_day + dd_np.to_f64(self.tdb_frac)
        utc = self.get_mjds()
        earth_pos, earth_vel = eph.ssb_posvel("earth", tdb)
        obs_pos = np.zeros((self.ntoas, 3))
        obs_vel = np.zeros((self.ntoas, 3))
        cache = getattr(self, "_site_gcrs_cache", {})
        for site in set(self.obs):
            m = np.array([o == site for o in self.obs])
            obs = get_observatory(site)
            if obs.name == "barycenter":
                # positions stay zero; earth contribution removed below
                continue
            cached = cache.get(site)
            if cached is not None and \
                    cached[0].sum() == int(m.sum()):
                # computed in compute_TDBs at the same epochs
                obs_pos[m] = cached[1]
                obs_vel[m] = cached[2]
                continue
            if hasattr(obs, "posvel_from_flags"):  # T2SpacecraftObs
                p, v = obs.posvel_from_flags(
                    [f for f, mm in zip(self.flags, m) if mm])
                obs_pos[m] = p
                obs_vel[m] = v
                continue
            p, v = obs.gcrs_posvel(utc[m], tdb[m])
            obs_pos[m] = p
            obs_vel[m] = v
        bary = np.array([o == "barycenter" for o in self.obs])
        ssb_obs_pos = earth_pos + obs_pos
        ssb_obs_vel = earth_vel + obs_vel
        if np.any(bary):
            ssb_obs_pos[bary] = 0.0
            ssb_obs_vel[bary] = 0.0
        self.ssb_obs_pos = ssb_obs_pos
        self.ssb_obs_vel = ssb_obs_vel
        sun_pos, _ = eph.ssb_posvel("sun", tdb)
        self.obs_sun_pos = sun_pos - ssb_obs_pos
        self.obs_planet_pos = {}
        if planets:
            for pl in PLANETS:
                p, _ = eph.ssb_posvel(pl, tdb)
                self.obs_planet_pos[pl] = p - ssb_obs_pos
        self._touch()

    # ---------------- device view ----------------

    def to_batch(self) -> ToaBatch:
        """Freeze into the device pytree (meters → light-seconds)."""
        if self.ssb_obs_pos is None:
            raise ValueError(
                "run compute_posvels() (or use get_TOAs) before to_batch()")
        pn = self.get_pulse_numbers()
        if pn is None:
            pn = np.full(self.ntoas, np.nan)
        planet = np.stack(
            [self.obs_planet_pos[p] for p in PLANETS], axis=0
        ) / c_m_s if self.obs_planet_pos else np.zeros((0, self.ntoas, 3))
        return ToaBatch(
            tdb_day=jnp.asarray(self.tdb_day),
            tdb_frac=DD(jnp.asarray(self.tdb_frac[0]),
                        jnp.asarray(self.tdb_frac[1])),
            freq_mhz=jnp.asarray(self.freq_mhz),
            error_us=jnp.asarray(self.error_us),
            ssb_obs_pos=jnp.asarray(self.ssb_obs_pos / c_m_s),
            ssb_obs_vel=jnp.asarray(self.ssb_obs_vel / c_m_s),
            obs_sun_pos=jnp.asarray(self.obs_sun_pos / c_m_s),
            obs_planet_pos=jnp.asarray(planet),
            pulse_number=jnp.asarray(pn),
        )

    def to_npz(self, path, cache_key=None):
        """Columnar snapshot of the fully-processed TOA table
        (reference: TOAs pickling via usepickle — npz here: no
        arbitrary code execution on load, stable across versions)."""
        import json

        arrays = {} if cache_key is None else \
            {"cache_key": np.array(cache_key)}
        arrays |= {
            "mjd_day": self.mjd_day,
            "mjd_frac_hi": self.mjd_frac[0],
            "mjd_frac_lo": self.mjd_frac[1],
            "freq_mhz": self.freq_mhz,
            "error_us": self.error_us,
            "obs": np.array(self.obs),
            "names": np.array(self.names),
            "flags_json": np.array(json.dumps(self.flags)),
            "meta_json": np.array(json.dumps({
                "clock_applied": bool(self.clock_applied),
                "ephem": self.ephem,
                "planets": bool(self.planets)})),
        }
        for col in ("tdb_day", "ssb_obs_pos", "ssb_obs_vel",
                    "obs_sun_pos"):
            v = getattr(self, col)
            if v is not None:
                arrays[col] = v
        if self.tdb_frac is not None:
            arrays["tdb_frac_hi"] = self.tdb_frac[0]
            arrays["tdb_frac_lo"] = self.tdb_frac[1]
        if self.obs_planet_pos is not None:
            arrays["planet_names"] = np.array(
                sorted(self.obs_planet_pos))
            for k, v in self.obs_planet_pos.items():
                arrays[f"planet_{k}"] = v
        # atomic: concurrent readers of a shared cache path must never
        # see a half-written file; tmp name is unique per thread too
        import threading
        import uuid

        tmp = (f"{path}.{os.getpid()}.{threading.get_ident()}."
               f"{uuid.uuid4().hex[:8]}.tmp")
        try:
            with open(tmp, "wb") as fh:
                np.savez_compressed(fh, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    @classmethod
    def from_npz(cls, path, expect_key=None) -> "TOAs":
        """Load a snapshot. ``expect_key``: verify the embedded cache
        key from the SAME open file handle the arrays come from (a
        separate check-then-load would race a concurrent overwrite of
        the shared cache path)."""
        import json

        with np.load(path, allow_pickle=False) as z:
            if expect_key is not None and (
                    "cache_key" not in z.files
                    or str(z["cache_key"]) != expect_key):
                raise ValueError("cache key mismatch")
            out = object.__new__(cls)
            out.mjd_day = z["mjd_day"]
            out.mjd_frac = (z["mjd_frac_hi"], z["mjd_frac_lo"])
            out.freq_mhz = z["freq_mhz"]
            out.error_us = z["error_us"]
            out.obs = [str(o) for o in z["obs"]]
            out.names = [str(n) for n in z["names"]]
            out.flags = json.loads(str(z["flags_json"]))
            meta = json.loads(str(z["meta_json"]))
            out.clock_applied = meta["clock_applied"]
            out.ephem = meta["ephem"]
            out.planets = meta["planets"]
            for col in ("tdb_day", "ssb_obs_pos", "ssb_obs_vel",
                        "obs_sun_pos"):
                setattr(out, col, z[col] if col in z.files else None)
            out.tdb_frac = (z["tdb_frac_hi"], z["tdb_frac_lo"]) \
                if "tdb_frac_hi" in z.files else None
            out.obs_planet_pos = None
            if "planet_names" in z.files:
                out.obs_planet_pos = {
                    str(k): z[f"planet_{k}"]
                    for k in z["planet_names"]}
        out._serial = next(_TOAS_SERIAL)
        return out

    def write_TOA_file(self, path):
        """Round-trip back to a FORMAT-1 tim file. Clock corrections, if
        applied, are subtracted so the file matches the original site
        clocks (reference: TOAs.write_TOA_file commentary)."""
        day, frac = self.mjd_day, self.mjd_frac
        if self.clock_applied:
            corr = np.array(
                [float(f.get("clkcorr", 0.0)) for f in self.flags])
            frac = dd_np.sub(frac, dd_np.div_f(dd_np.dd(corr), SECS_PER_DAY))
        out = []
        for i in range(self.ntoas):
            flags = {k: v for k, v in self.flags[i].items()
                     if k not in ("clkcorr", "to")}
            out.append(TimTOA(
                mjd_str=mjdmod.mjd_to_str(day[i], (frac[0][i], frac[1][i])),
                freq_mhz=float(self.freq_mhz[i])
                if np.isfinite(self.freq_mhz[i]) else 0.0,
                error_us=float(self.error_us[i]),
                obs=self.obs[i], name=self.names[i] or f"toa{i}",
                flags=flags))
        write_tim(path, out)


def save_pickle(toas: TOAs, picklefilename: str) -> None:
    """Pickle a TOAs object (reference: toa.save_pickle). The npz
    columnar cache (TOAs.to_npz) is the preferred persistent format —
    no code execution on load — but the reference's pickle entry
    points are provided for API parity."""
    import pickle

    with open(picklefilename, "wb") as fh:
        pickle.dump(toas, fh, protocol=pickle.HIGHEST_PROTOCOL)


def load_pickle(picklefilename: str) -> TOAs:
    """Unpickle a TOAs object (reference: toa.load_pickle). Only load
    files you wrote yourself — pickle executes code on load; prefer
    TOAs.from_npz for shared caches."""
    import pickle

    with open(picklefilename, "rb") as fh:
        out = pickle.load(fh)
    if not isinstance(out, TOAs):
        raise TypeError(f"{picklefilename!r} did not contain a TOAs "
                        f"object (got {type(out).__name__})")
    out._serial = next(_TOAS_SERIAL)
    return out


def merge_TOAs(toas_list: List[TOAs]) -> TOAs:
    """Concatenate TOA sets (reference: merge_TOAs). All inputs must be
    at the same pipeline stage."""
    first = toas_list[0]
    out = object.__new__(TOAs)
    out.mjd_day = np.concatenate([t.mjd_day for t in toas_list])
    out.mjd_frac = (
        np.concatenate([t.mjd_frac[0] for t in toas_list]),
        np.concatenate([t.mjd_frac[1] for t in toas_list]))
    out.freq_mhz = np.concatenate([t.freq_mhz for t in toas_list])
    out.error_us = np.concatenate([t.error_us for t in toas_list])
    out.obs = sum((t.obs for t in toas_list), [])
    out.flags = sum(([dict(f) for f in t.flags] for t in toas_list), [])
    out.names = sum((t.names for t in toas_list), [])
    out.clock_applied = first.clock_applied
    out.ephem = first.ephem
    out.planets = first.planets
    stages = {t.clock_applied for t in toas_list}
    if len(stages) > 1:
        raise ValueError("cannot merge TOAs at different pipeline stages")
    for col in ("tdb_day", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
        vals = [getattr(t, col) for t in toas_list]
        setattr(out, col,
                None if any(v is None for v in vals)
                else np.concatenate(vals))
    fracs = [t.tdb_frac for t in toas_list]
    out.tdb_frac = None if any(f is None for f in fracs) else (
        np.concatenate([f[0] for f in fracs]),
        np.concatenate([f[1] for f in fracs]))
    pls = [t.obs_planet_pos for t in toas_list]
    if any(p is None for p in pls):
        out.obs_planet_pos = None
    elif any(bool(p) != bool(pls[0]) for p in pls):
        raise ValueError(
            "cannot merge TOAs with and without planet positions; "
            "recompute with a consistent planets= setting")
    elif not pls[0]:
        out.obs_planet_pos = {}
    else:
        out.obs_planet_pos = {
            k: np.concatenate([p[k] for p in pls]) for k in pls[0]}
    out._serial = next(_TOAS_SERIAL)
    return out


def get_TOAs(timfile, ephem=None, planets=False, model=None,
             include_gps=True, include_bipm=True, bipm_version="BIPM2021",
             limits="warn", usecache=False, cachedir=None) -> TOAs:
    """One-call ingestion pipeline: parse → clock → TDB → posvels
    (reference: src/pint/toa.py get_TOAs).

    With ``usecache`` (reference: usepickle), the fully-processed TOAs
    are stored as a columnar npz next to the tim file (or in
    ``cachedir``), keyed on a hash of the tim content and every
    pipeline knob; a stale or mismatched cache is rebuilt silently."""
    if model is not None:
        if ephem is None:
            ephem = getattr(model, "EPHEM", None) and model.EPHEM.value
        if not planets:
            ps = getattr(model, "PLANET_SHAPIRO", None)
            planets = bool(ps is not None and ps.value)
    cache_path = cache_key = None
    if usecache and isinstance(timfile, (str, os.PathLike)):
        import hashlib

        from pint_tpu import __version__

        fpath = os.fspath(timfile)
        try:
            with open(fpath, "rb") as fh:
                digest = hashlib.sha256(fh.read())
        except OSError:
            digest = None
        if digest is not None:
            # key = tim content + every pipeline knob + the package
            # version + clock/EOP override dirs, so numerics fixes and
            # swapped correction tables invalidate old caches
            digest.update(repr((
                ephem, planets, include_gps, include_bipm,
                bipm_version, __version__,
                _env_dir_key(config.clock_dir()),
                _env_dir_key(config.ephem_dir()))).encode())
            cache_key = digest.hexdigest()
            base = os.path.basename(fpath)
            cdir = cachedir or os.path.dirname(os.path.abspath(fpath))
            # ONE cache file per tim file; the key lives inside so a
            # mismatch overwrites in place instead of accumulating
            cache_path = os.path.join(cdir, f".{base}.toacache.npz")
            if os.path.exists(cache_path):
                try:
                    # key checked and arrays read under ONE open: a
                    # concurrent overwrite can't swap the file between
                    # validation and load
                    return TOAs.from_npz(cache_path,
                                         expect_key=cache_key)
                except Exception:
                    pass  # stale/corrupt cache: rebuild below
    t = TOAs(parse_tim(timfile))
    t.apply_clock_corrections(include_gps=include_gps,
                              include_bipm=include_bipm,
                              bipm_version=bipm_version, limits=limits)
    t.compute_TDBs(ephem=ephem)
    t.compute_posvels(ephem=ephem, planets=planets)
    if cache_path is not None:
        try:
            t.to_npz(cache_path, cache_key=cache_key)
            # sweep hashed-sibling caches from the old naming scheme
            # ONLY (exact `.{base}.<16 hex>.npz` names — a loose glob
            # would eat sibling tim files' valid caches, e.g.
            # `.x.tim.bak.toacache.npz` matching `.x.tim.*`)
            import glob as _glob
            import re as _re

            pat = _re.compile(
                _re.escape(f".{base}.") + r"[0-9a-f]{16}\.npz$")
            for old in _glob.glob(os.path.join(
                    os.path.dirname(cache_path), f".{base}.*.npz")):
                if pat.search(os.path.basename(old)):
                    try:
                        os.unlink(old)
                    except OSError:
                        pass
        except OSError:
            pass  # read-only dir: caching is best-effort
    return t


def get_TOAs_array(mjds, obs="barycenter", freqs=np.inf, errors=1.0,
                   ephem=None, planets=False, flags=None, include_gps=True,
                   include_bipm=True, bipm_version="BIPM2021",
                   limits="warn") -> TOAs:
    """Build TOAs directly from arrays (reference: get_TOAs_array). mjds
    may be f64 (splitting day/frac) or an (day, frac-dd) pair."""
    if isinstance(mjds, tuple):
        day, frac = mjds
        day = np.asarray(day, np.float64)
        frac = (np.asarray(frac[0], np.float64),
                np.asarray(frac[1], np.float64))
    else:
        m = np.atleast_1d(np.asarray(mjds, np.float64))
        day = np.floor(m)
        frac = dd_np.dd(m - day)
    day = np.atleast_1d(day)
    frac = (np.atleast_1d(frac[0]), np.atleast_1d(frac[1]))
    n = day.shape[0]
    freqs = np.broadcast_to(np.asarray(freqs, np.float64), (n,))
    errors = np.broadcast_to(np.asarray(errors, np.float64), (n,))
    obs_list = [obs] * n if isinstance(obs, str) else list(obs)
    out = object.__new__(TOAs)
    out.mjd_day = day
    out.mjd_frac = frac
    out.freq_mhz = np.array(freqs)
    out.error_us = np.array(errors)
    out.obs = [get_observatory(o).name for o in obs_list]
    out.flags = [dict(f) for f in flags] if flags is not None \
        else [{} for _ in range(n)]
    out.names = [f"fake{i}" for i in range(n)]
    out._serial = next(_TOAS_SERIAL)
    out.clock_applied = False
    out.tdb_day = None
    out.tdb_frac = None
    out.ssb_obs_pos = out.ssb_obs_vel = out.obs_sun_pos = None
    out.obs_planet_pos = None
    out.ephem = None
    out.planets = planets
    out.apply_clock_corrections(include_gps=include_gps,
                                include_bipm=include_bipm,
                                bipm_version=bipm_version, limits=limits)
    out.compute_TDBs(ephem=ephem)
    out.compute_posvels(ephem=ephem, planets=planets)
    return out
