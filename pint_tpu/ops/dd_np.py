"""Host-side (numpy) double-double arithmetic — same algorithms as
``pint_tpu.ops.dd`` but on plain numpy arrays.

Host x86 f64 is IEEE-correctly-rounded, so error-free transforms are exact
here unconditionally (unlike TPU-under-jit — see ARCHITECTURE.md). Used by
the ingestion/precompute layer (MJD string parsing, time-scale chains,
reference-phase assembly) where JAX brings nothing and the axon platform
pin makes CPU-backend JAX awkward.

Values are (hi, lo) ndarray pairs; functions mirror the JAX module 1:1.
"""

from __future__ import annotations

import numpy as np

_SPLITTER = 134217729.0


def two_sum(a, b):
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def quick_two_sum(a, b):
    s = a + b
    return s, b - (s - a)


def two_prod(a, b):
    p = a * b
    t = _SPLITTER * a
    ah = t - (t - a)
    al = a - ah
    t = _SPLITTER * b
    bh = t - (t - b)
    bl = b - bh
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def dd(hi, lo=0.0):
    hi = np.asarray(hi, dtype=np.float64)
    lo = np.broadcast_to(np.asarray(lo, dtype=np.float64), np.broadcast(hi, lo).shape)
    hi = np.broadcast_to(hi, lo.shape)
    s, e = two_sum(hi, lo)
    return quick_two_sum(s, e)


def add(a, b):
    s, e = two_sum(a[0], b[0])
    e = e + (a[1] + b[1])
    return quick_two_sum(s, e)


def add_f(a, b):
    s, e = two_sum(a[0], np.asarray(b, np.float64))
    return quick_two_sum(s, e + a[1])


def sub(a, b):
    return add(a, (-b[0], -b[1]))


def sub_f(a, b):
    return add_f(a, -np.asarray(b, np.float64))


def mul(a, b):
    p, e = two_prod(a[0], b[0])
    e = e + (a[0] * b[1] + a[1] * b[0])
    return quick_two_sum(p, e)


def mul_f(a, b):
    b = np.asarray(b, np.float64)
    p, e = two_prod(a[0], b)
    return quick_two_sum(p, e + a[1] * b)


def div(a, b):
    q1 = a[0] / b[0]
    r = sub(a, mul_f(b, q1))
    q2 = (r[0] + r[1]) / (b[0] + b[1])
    return quick_two_sum(q1, q2)


def div_f(a, b):
    return div(a, dd(b))


def neg(a):
    return (-a[0], -a[1])


def to_f64(a):
    return a[0] + a[1]


def dd_round(a):
    n = np.round(a[0])
    r = (a[0] - n) + a[1]
    bump = np.where(r > 0.5, 1.0, 0.0) + np.where(r < -0.5, -1.0, 0.0)
    return dd(n + bump)


def frac(a):
    """Signed fractional part in [-0.5, 0.5]: a - round(a)."""
    n = np.round(a[0])
    s, se = two_sum(a[0], -n)
    f, fe = two_sum(s, a[1])
    f, fe = quick_two_sum(f, fe + se)
    shift = np.where(f > 0.5, 1.0, 0.0) + np.where(f < -0.5, -1.0, 0.0)
    s2, s2e = two_sum(f, -shift)
    g, ge = two_sum(s2, fe)
    return quick_two_sum(g, ge + s2e)


def taylor_horner(dt, coeffs):
    """sum_i coeffs[i] dt^i / i! with dd accumulator; dt is a dd pair,
    coeffs are f64 scalars or dd pairs."""
    import math

    acc = dd(np.zeros_like(dt[0]))
    for i in reversed(range(len(coeffs))):
        ci = coeffs[i]
        fct = float(math.factorial(i))
        acc = mul(acc, dt)
        if isinstance(ci, tuple):
            acc = add(acc, div_f(ci, fct) if fct != 1.0 else ci)
        else:
            acc = add_f(acc, np.float64(ci) / fct)
    return acc
