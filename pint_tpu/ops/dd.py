"""Double-double ("dd") arithmetic: each value is an unevaluated sum
``hi + lo`` of two float64, giving ~32 significant digits (eps ~ 2^-104).

This is the TPU-native replacement for the reference's load-bearing use of
x87 ``np.longdouble`` (eps 1.08e-19) in time/phase bookkeeping
(reference: src/pint/pulsar_mjd.py, src/pint/phase.py Phase). TPU has no
extended-precision type, but f64 pairs exceed longdouble precision
(~1e-32 relative), so pulse phase stays exact to ≪1 ns over centuries.

Design notes (TPU/XLA-first):

- ``DD`` is a NamedTuple pytree of two f64 arrays → flows through
  jit/vmap/scan/shard_map like any array pair; elementwise ops fuse in XLA.
- Error-free transforms use Knuth two-sum and Dekker/Veltkamp split
  two-product (no FMA primitive is exposed portably through jnp; the split
  product is exact in round-to-nearest f64, which XLA:TPU honors for f64).
- The user-facing ops carry ``jax.custom_jvp`` rules whose tangents are
  plain first-order f64 rules. This keeps autodiff (the design-matrix
  path, reference: TimingModel.designmatrix) from tracing through the
  error-term algebra: derivatives never need 32 digits, residual *values*
  do.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

Arr = jax.Array
FloatLike = Union[float, Arr]

_SPLITTER = 134217729.0  # 2**27 + 1, Veltkamp splitting constant for f64


class DD(NamedTuple):
    """Unevaluated sum hi + lo, |lo| <= ulp(hi)/2 after renormalization."""

    hi: Arr
    lo: Arr

    # Convenience operators — thin sugar over the module functions so call
    # sites in model code read naturally. All return DD.
    def __add__(self, other):
        return dd_add(self, _as_dd(other))

    def __radd__(self, other):
        return dd_add(_as_dd(other), self)

    def __sub__(self, other):
        return dd_sub(self, _as_dd(other))

    def __rsub__(self, other):
        return dd_sub(_as_dd(other), self)

    def __mul__(self, other):
        return dd_mul(self, _as_dd(other))

    def __rmul__(self, other):
        return dd_mul(_as_dd(other), self)

    def __truediv__(self, other):
        return dd_div(self, _as_dd(other))

    def __neg__(self):
        return dd_neg(self)


def _as_dd(x) -> DD:
    if isinstance(x, DD):
        return x
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


def dd(hi, lo=0.0) -> DD:
    """Construct a DD from one or two float64 values (renormalized).

    Uses full two-sum: callers may pass unnormalized (hi, lo) of any
    relative magnitude.
    """
    hi, lo = jnp.broadcast_arrays(
        jnp.asarray(hi, dtype=jnp.float64), jnp.asarray(lo, dtype=jnp.float64)
    )
    s = two_sum(hi, lo)
    return _quick_two_sum(s.hi, s.lo)


def dd_from_parts(hi, lo) -> DD:
    """Trusted constructor: caller guarantees (hi, lo) already normalized."""
    return DD(jnp.asarray(hi, jnp.float64), jnp.asarray(lo, jnp.float64))


def dd_to_f64(a: DD) -> Arr:
    return a.hi + a.lo


# ----------------------------------------------------------------------
# Error-free transforms (internal; plain f64 ops, exact by construction)
# ----------------------------------------------------------------------

def two_sum(a: Arr, b: Arr) -> DD:
    """Knuth two-sum: s + err == a + b exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return DD(s, err)


def _quick_two_sum(a: Arr, b: Arr) -> DD:
    """Fast two-sum, requires |a| >= |b| (or a == 0)."""
    s = a + b
    err = b - (s - a)
    return DD(s, err)


def _split(a: Arr):
    t = _SPLITTER * a
    a_hi = t - (t - a)
    a_lo = a - a_hi
    return a_hi, a_lo


def two_prod(a: Arr, b: Arr) -> DD:
    """Dekker two-product: p + err == a * b exactly (round-to-nearest)."""
    p = a * b
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    err = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return DD(p, err)


# ----------------------------------------------------------------------
# DD arithmetic. Each public op has a custom JVP with plain-f64 tangents.
# ----------------------------------------------------------------------

@jax.custom_jvp
def dd_add(a: DD, b: DD) -> DD:
    s = two_sum(a.hi, b.hi)
    e = s.lo + (a.lo + b.lo)
    return _quick_two_sum(s.hi, e)


@dd_add.defjvp
def _dd_add_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    t = (da.hi + da.lo) + (db.hi + db.lo)
    return dd_add(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_sub(a: DD, b: DD) -> DD:
    s = two_sum(a.hi, -b.hi)
    e = s.lo + (a.lo - b.lo)
    return _quick_two_sum(s.hi, e)


@dd_sub.defjvp
def _dd_sub_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    t = (da.hi + da.lo) - (db.hi + db.lo)
    return dd_sub(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_mul(a: DD, b: DD) -> DD:
    p = two_prod(a.hi, b.hi)
    e = p.lo + (a.hi * b.lo + a.lo * b.hi)
    return _quick_two_sum(p.hi, e)


@dd_mul.defjvp
def _dd_mul_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    av = a.hi + a.lo
    bv = b.hi + b.lo
    t = (da.hi + da.lo) * bv + (db.hi + db.lo) * av
    return dd_mul(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_div(a: DD, b: DD) -> DD:
    # Long division with one Newton correction — standard dd recipe.
    q1 = a.hi / b.hi
    r = dd_sub(a, dd_mul_f(b, q1))
    q2 = (r.hi + r.lo) / (b.hi + b.lo)
    return _quick_two_sum(q1, q2)


@dd_div.defjvp
def _dd_div_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    av = a.hi + a.lo
    bv = b.hi + b.lo
    q = dd_div(a, b)
    t = ((da.hi + da.lo) - (db.hi + db.lo) * (av / bv)) / bv
    return q, DD(t, jnp.zeros_like(t))


def dd_neg(a: DD) -> DD:
    return DD(-a.hi, -a.lo)


def dd_abs(a: DD) -> DD:
    neg = a.hi < 0
    return DD(jnp.where(neg, -a.hi, a.hi), jnp.where(neg, -a.lo, a.lo))


# f64-mixed fast paths (second operand an ordinary float64)

def dd_add_f(a: DD, b: FloatLike) -> DD:
    b = jnp.asarray(b, jnp.float64)
    s = two_sum(a.hi, b)
    return _quick_two_sum(s.hi, s.lo + a.lo)


def dd_sub_f(a: DD, b: FloatLike) -> DD:
    return dd_add_f(a, -jnp.asarray(b, jnp.float64))


def dd_mul_f(a: DD, b: FloatLike) -> DD:
    b = jnp.asarray(b, jnp.float64)
    p = two_prod(a.hi, b)
    return _quick_two_sum(p.hi, p.lo + a.lo * b)


def dd_div_f(a: DD, b: FloatLike) -> DD:
    return dd_div(a, _as_dd(b))


# ----------------------------------------------------------------------
# Rounding / fractional part — the pulse-number primitives
# (reference: src/pint/phase.py Phase int/frac decomposition)
# ----------------------------------------------------------------------

@jax.custom_jvp
def dd_round(a: DD) -> DD:
    """Round to nearest integer, returned as DD (exact)."""
    n = jnp.round(a.hi)
    # hi - n is exact (Sterbenz) whenever |hi - n| <= 0.5 ulp-scale; the
    # residual plus lo decides whether rounding must be bumped by one.
    r = (a.hi - n) + a.lo
    bump = jnp.where(r > 0.5, 1.0, 0.0) + jnp.where(r < -0.5, -1.0, 0.0)
    return dd(n + bump)


@dd_round.defjvp
def _dd_round_jvp(primals, tangents):
    (a,) = primals
    (da,) = tangents
    z = jnp.zeros_like(a.hi)
    return dd_round(a), DD(z, z)


@jax.custom_jvp
def dd_frac(a: DD) -> DD:
    """Signed fractional part in [-0.5, 0.5]: a - round(a), exact.

    This is the "phase.frac" of the reference's Phase class — residuals in
    turns. d(frac)/dx == 1 away from half-integers, which the JVP encodes.
    """
    n = jnp.round(a.hi)
    s = two_sum(a.hi, -n)
    # s.hi may be ≪ a.lo when a is nearly integer — full two_sum required.
    f0 = two_sum(s.hi, a.lo)
    f = _quick_two_sum(f0.hi, f0.lo + s.lo)
    # renormalize into [-0.5, 0.5]
    shift = jnp.where(f.hi > 0.5, 1.0, 0.0) + jnp.where(f.hi < -0.5, -1.0, 0.0)
    s2 = two_sum(f.hi, -shift)
    f1 = two_sum(s2.hi, f.lo)
    return _quick_two_sum(f1.hi, f1.lo + s2.lo)


@dd_frac.defjvp
def _dd_frac_jvp(primals, tangents):
    (a,) = primals
    (da,) = tangents
    t = da.hi + da.lo
    return dd_frac(a), DD(t, jnp.zeros_like(t))


def dd_int_frac(a: DD):
    """(integer part as DD, signed frac in [-0.5, 0.5] as DD)."""
    n = dd_round(a)
    return n, dd_frac(a)


# ----------------------------------------------------------------------
# Comparisons (value-level; return bool arrays)
# ----------------------------------------------------------------------

def dd_lt(a: DD, b: DD) -> Arr:
    return (a.hi < b.hi) | ((a.hi == b.hi) & (a.lo < b.lo))


def dd_le(a: DD, b: DD) -> Arr:
    return (a.hi < b.hi) | ((a.hi == b.hi) & (a.lo <= b.lo))


def dd_where(cond: Arr, a: DD, b: DD) -> DD:
    return DD(jnp.where(cond, a.hi, b.hi), jnp.where(cond, a.lo, b.lo))


def dd_sum(a: DD, axis=None) -> DD:
    """Sum of a DD array along axis with compensated (Neumaier-style)
    accumulation of the hi chain; los are summed plainly (they are already
    ~1e-16 relative, their rounding error is ~1e-32 relative — negligible).
    """
    if axis is None:
        a = DD(a.hi.ravel(), a.lo.ravel())
        axis = 0
    s = jnp.cumsum(a.hi, axis=axis)
    n = a.hi.shape[axis]
    prev = jnp.concatenate(
        [jnp.zeros_like(jax.lax.slice_in_dim(s, 0, 1, axis=axis)),
         jax.lax.slice_in_dim(s, 0, n - 1, axis=axis)],
        axis=axis,
    )
    # exact error of each step s_i = prev_i + x_i (Knuth two-sum error term)
    bb = s - prev
    err = (prev - (s - bb)) + (a.hi - bb)
    hi_s = jax.lax.index_in_dim(s, n - 1, axis=axis, keepdims=False)
    lo_s = jnp.sum(err, axis=axis) + jnp.sum(a.lo, axis=axis)
    return _quick_two_sum(hi_s, lo_s)
