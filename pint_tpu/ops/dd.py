"""Double-double ("dd") arithmetic: each value is an unevaluated sum
``hi + lo`` of two float64, giving ~32 significant digits (eps ~ 2^-104).

This is the TPU-native replacement for the reference's load-bearing use of
x87 ``np.longdouble`` (eps 1.08e-19) in time/phase bookkeeping
(reference: src/pint/pulsar_mjd.py, src/pint/phase.py Phase). TPU has no
extended-precision type, but f64 pairs exceed longdouble precision
(~1e-32 relative), so pulse phase stays exact to ≪1 ns over centuries.

Design notes (TPU/XLA-first):

- ``DD`` is a NamedTuple pytree of two f64 arrays → flows through
  jit/vmap/scan/shard_map like any array pair; elementwise ops fuse in XLA.
- Error-free transforms use Knuth two-sum and Dekker/Veltkamp split
  two-product (no FMA primitive is exposed portably through jnp; the split
  product is exact in round-to-nearest f64, which XLA:TPU honors for f64).
- The user-facing ops carry ``jax.custom_jvp`` rules whose tangents are
  plain first-order f64 rules. This keeps autodiff (the design-matrix
  path, reference: TimingModel.designmatrix) from tracing through the
  error-term algebra: derivatives never need 32 digits, residual *values*
  do.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

Arr = jax.Array
FloatLike = Union[float, Arr]

# Veltkamp splitting constants: 2**ceil(p/2) + 1 for a p-bit mantissa.
# dd is dtype-generic: f64 pairs give ~2^-104 (the precision path), f32
# pairs ("dd32") give ~2^-48 — the same effective precision as TPU's
# software-emulated f64, but in native-speed f32 vector ops. The f32
# Jacobian path (parallel/fit_step) runs the whole phase chain in dd32.
_SPLITTER_F64 = 134217729.0   # 2**27 + 1
_SPLITTER_F32 = 4097.0        # 2**12 + 1


class DD(NamedTuple):
    """Unevaluated sum hi + lo, |lo| <= ulp(hi)/2 after renormalization."""

    hi: Arr
    lo: Arr

    # Convenience operators — thin sugar over the module functions so call
    # sites in model code read naturally. All return DD.
    def __add__(self, other):
        return dd_add(self, _as_dd(other))

    def __radd__(self, other):
        return dd_add(_as_dd(other), self)

    def __sub__(self, other):
        return dd_sub(self, _as_dd(other))

    def __rsub__(self, other):
        return dd_sub(_as_dd(other), self)

    def __mul__(self, other):
        return dd_mul(self, _as_dd(other))

    def __rmul__(self, other):
        return dd_mul(_as_dd(other), self)

    def __truediv__(self, other):
        return dd_div(self, _as_dd(other))

    def __neg__(self):
        return dd_neg(self)


def _float_dtype(*xs):
    """f32 only when every operand is f32; anything else promotes to
    f64 (so a deliberately-carried f64 compensation term is never
    silently truncated)."""
    dts = [jnp.asarray(x).dtype for x in xs]
    if all(dt == jnp.float32 for dt in dts):
        return jnp.float32
    return jnp.float64


def _as_dd(x) -> DD:
    if isinstance(x, DD):
        return x
    x = jnp.asarray(x, dtype=_float_dtype(x))
    return DD(x, jnp.zeros_like(x))


def dd(hi, lo=0.0) -> DD:
    """Construct a DD from one or two float values (renormalized);
    dtype follows the inputs (f64 unless both are f32).

    Uses full two-sum: callers may pass unnormalized (hi, lo) of any
    relative magnitude.
    """
    dt = _float_dtype(hi, lo)
    hi, lo = jnp.broadcast_arrays(
        jnp.asarray(hi, dtype=dt), jnp.asarray(lo, dtype=dt)
    )
    s = two_sum(hi, lo)
    return _quick_two_sum(s.hi, s.lo)


def dd_from_parts(hi, lo) -> DD:
    """Trusted constructor: caller guarantees (hi, lo) already normalized."""
    dt = _float_dtype(hi, lo)
    return DD(jnp.asarray(hi, dt), jnp.asarray(lo, dt))


def dd_to_f64(a: DD) -> Arr:
    return a.hi + a.lo


# ----------------------------------------------------------------------
# Error-free transforms (internal; plain f64 ops, exact by construction)
# ----------------------------------------------------------------------

def two_sum(a: Arr, b: Arr) -> DD:
    """Knuth two-sum: s + err == a + b exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return DD(s, err)


def _quick_two_sum(a: Arr, b: Arr) -> DD:
    """Fast two-sum, requires |a| >= |b| (or a == 0)."""
    s = a + b
    err = b - (s - a)
    return DD(s, err)


def _split(a: Arr):
    splitter = (_SPLITTER_F32 if jnp.asarray(a).dtype == jnp.float32
                else _SPLITTER_F64)
    t = splitter * a
    a_hi = t - (t - a)
    a_lo = a - a_hi
    return a_hi, a_lo


def two_prod(a: Arr, b: Arr) -> DD:
    """Dekker two-product: p + err == a * b exactly (round-to-nearest)."""
    p = a * b
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    err = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return DD(p, err)


# ----------------------------------------------------------------------
# DD arithmetic. Each public op has a custom JVP with plain-f64 tangents.
# ----------------------------------------------------------------------

@jax.custom_jvp
def dd_add(a: DD, b: DD) -> DD:
    s = two_sum(a.hi, b.hi)
    e = s.lo + (a.lo + b.lo)
    return _quick_two_sum(s.hi, e)


@dd_add.defjvp
def _dd_add_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    t = (da.hi + da.lo) + (db.hi + db.lo)
    return dd_add(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_sub(a: DD, b: DD) -> DD:
    s = two_sum(a.hi, -b.hi)
    e = s.lo + (a.lo - b.lo)
    return _quick_two_sum(s.hi, e)


@dd_sub.defjvp
def _dd_sub_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    t = (da.hi + da.lo) - (db.hi + db.lo)
    return dd_sub(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_mul(a: DD, b: DD) -> DD:
    p = two_prod(a.hi, b.hi)
    e = p.lo + (a.hi * b.lo + a.lo * b.hi)
    return _quick_two_sum(p.hi, e)


@dd_mul.defjvp
def _dd_mul_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    av = a.hi + a.lo
    bv = b.hi + b.lo
    t = (da.hi + da.lo) * bv + (db.hi + db.lo) * av
    return dd_mul(a, b), DD(t, jnp.zeros_like(t))


@jax.custom_jvp
def dd_div(a: DD, b: DD) -> DD:
    # Long division with one Newton correction — standard dd recipe.
    q1 = a.hi / b.hi
    r = dd_sub(a, dd_mul_f(b, q1))
    q2 = (r.hi + r.lo) / (b.hi + b.lo)
    return _quick_two_sum(q1, q2)


@dd_div.defjvp
def _dd_div_jvp(primals, tangents):
    a, b = primals
    da, db = tangents
    av = a.hi + a.lo
    bv = b.hi + b.lo
    q = dd_div(a, b)
    t = ((da.hi + da.lo) - (db.hi + db.lo) * (av / bv)) / bv
    return q, DD(t, jnp.zeros_like(t))


def dd_neg(a: DD) -> DD:
    return DD(-a.hi, -a.lo)


def dd_abs(a: DD) -> DD:
    neg = a.hi < 0
    return DD(jnp.where(neg, -a.hi, a.hi), jnp.where(neg, -a.lo, a.lo))


# f64-mixed fast paths (second operand an ordinary float64)

def dd_add_f(a: DD, b: FloatLike) -> DD:
    b = jnp.asarray(b, a.hi.dtype)
    s = two_sum(a.hi, b)
    return _quick_two_sum(s.hi, s.lo + a.lo)


def dd_sub_f(a: DD, b: FloatLike) -> DD:
    return dd_add_f(a, -jnp.asarray(b, a.hi.dtype))


def dd_mul_f(a: DD, b: FloatLike) -> DD:
    b = jnp.asarray(b, a.hi.dtype)
    p = two_prod(a.hi, b)
    return _quick_two_sum(p.hi, p.lo + a.lo * b)


def dd_div_f(a: DD, b: FloatLike) -> DD:
    # cast b to a's dtype (like add_f/mul_f): _as_dd would type a bare
    # Python float as f64 and silently promote a dd32 chain
    b = jnp.asarray(b, a.hi.dtype)
    return dd_div(a, DD(b, jnp.zeros_like(b)))


# ----------------------------------------------------------------------
# Rounding / fractional part — the pulse-number primitives
# (reference: src/pint/phase.py Phase int/frac decomposition)
# ----------------------------------------------------------------------

@jax.custom_jvp
def dd_round(a: DD) -> DD:
    """Round to nearest integer, returned as DD (exact).

    Works at any |lo|/1 ratio: when ulp(hi) > 1 (dd32 at large
    magnitude) the residual-plus-lo correction is itself a multi-unit
    integer, so it is rounded rather than clamped to ±1, and the two
    pieces are recombined exactly via two-sum in the dd() constructor."""
    n1 = jnp.round(a.hi)
    s = two_sum(a.hi, -n1)
    r = (s.hi + a.lo) + s.lo
    bump = jnp.round(r)
    return dd(n1, bump)


@dd_round.defjvp
def _dd_round_jvp(primals, tangents):
    (a,) = primals
    (da,) = tangents
    z = jnp.zeros_like(a.hi)
    return dd_round(a), DD(z, z)


@jax.custom_jvp
def dd_frac(a: DD) -> DD:
    """Signed fractional part in [-0.5, 0.5]: a - round(a), exact.

    This is the "phase.frac" of the reference's Phase class — residuals in
    turns. d(frac)/dx == 1 away from half-integers, which the JVP encodes.
    """
    # first integer strip of hi (two_sum remainder is exact)
    n1 = jnp.round(a.hi)
    s = two_sum(a.hi, -n1)
    # fold in lo; when ulp(hi) > 1 (dd32 at large magnitude) |lo| can
    # span many units, so a second integer strip of the recombined
    # value is required before the final half-boundary shift
    t = two_sum(s.hi, a.lo)
    vhi, vlo = t.hi, t.lo + s.lo
    n2 = jnp.round(vhi)
    s2 = two_sum(vhi, -n2)
    f0 = two_sum(s2.hi, vlo)
    f = _quick_two_sum(f0.hi, f0.lo + s2.lo)
    # renormalize into [-0.5, 0.5]
    shift = jnp.where(f.hi > 0.5, 1.0, 0.0) + jnp.where(f.hi < -0.5, -1.0, 0.0)
    s3 = two_sum(f.hi, -shift)
    f1 = two_sum(s3.hi, f.lo)
    return _quick_two_sum(f1.hi, f1.lo + s3.lo)


@dd_frac.defjvp
def _dd_frac_jvp(primals, tangents):
    (a,) = primals
    (da,) = tangents
    t = da.hi + da.lo
    return dd_frac(a), DD(t, jnp.zeros_like(t))


def dd_int_frac(a: DD):
    """(integer part as DD, signed frac in [-0.5, 0.5] as DD)."""
    n = dd_round(a)
    return n, dd_frac(a)


# ----------------------------------------------------------------------
# Comparisons (value-level; return bool arrays)
# ----------------------------------------------------------------------

def dd_lt(a: DD, b: DD) -> Arr:
    return (a.hi < b.hi) | ((a.hi == b.hi) & (a.lo < b.lo))


def dd_le(a: DD, b: DD) -> Arr:
    return (a.hi < b.hi) | ((a.hi == b.hi) & (a.lo <= b.lo))


def dd_where(cond: Arr, a: DD, b: DD) -> DD:
    return DD(jnp.where(cond, a.hi, b.hi), jnp.where(cond, a.lo, b.lo))


# ----------------------------------------------------------------------
# f64 <-> dd32 conversion (the f32 Jacobian path's input packing)
# ----------------------------------------------------------------------

def f64_to_dd32(x) -> DD:
    """Split a float64 value into an f32 pair (hi, lo) with
    hi + lo == x to ~2^-48 relative — the dd32 representation the f32
    design-matrix path consumes. Host- or device-side."""
    import numpy as np

    if isinstance(x, jax.Array):
        hi = x.astype(jnp.float32)
        lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
        return DD(hi, lo)
    x = np.asarray(x, np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return DD(hi, lo)


def dd_to_dd32(a: DD) -> DD:
    """Narrow a dd64 to dd32 (~2^-48): hi32 takes the top 24 bits,
    lo32 the next 24 plus whatever of a.lo still fits."""
    import numpy as np

    if isinstance(a.hi, jax.Array):
        hi = a.hi.astype(jnp.float32)
        rem = (a.hi - hi.astype(jnp.float64)) + a.lo
        return DD(hi, rem.astype(jnp.float32))
    hi = np.asarray(a.hi, np.float64).astype(np.float32)
    rem = (np.asarray(a.hi, np.float64) - hi.astype(np.float64)) \
        + np.asarray(a.lo, np.float64)
    return DD(hi, rem.astype(np.float32))


def dd_sum(a: DD, axis=None) -> DD:
    """Sum of a DD array along axis with compensated (Neumaier-style)
    accumulation of the hi chain; los are summed plainly (they are already
    ~1e-16 relative, their rounding error is ~1e-32 relative — negligible).
    """
    if axis is None:
        a = DD(a.hi.ravel(), a.lo.ravel())
        axis = 0
    s = jnp.cumsum(a.hi, axis=axis)
    n = a.hi.shape[axis]
    prev = jnp.concatenate(
        [jnp.zeros_like(jax.lax.slice_in_dim(s, 0, 1, axis=axis)),
         jax.lax.slice_in_dim(s, 0, n - 1, axis=axis)],
        axis=axis,
    )
    # exact error of each step s_i = prev_i + x_i (Knuth two-sum error term)
    bb = s - prev
    err = (prev - (s - bb)) + (a.hi - bb)
    hi_s = jax.lax.index_in_dim(s, n - 1, axis=axis, keepdims=False)
    lo_s = jnp.sum(err, axis=axis) + jnp.sum(a.lo, axis=axis)
    return _quick_two_sum(hi_s, lo_s)
