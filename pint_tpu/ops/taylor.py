"""Taylor-series evaluation kernels.

``taylor_horner(dt, [c0, c1, c2, ...]) = c0 + c1 dt + c2 dt^2/2! + ...``
is the spindown phase engine of the reference
(src/pint/utils.py taylor_horner / taylor_horner_deriv;
src/pint/models/spindown.py Spindown.spindown_phase).

Two variants here:
- plain f64 (for delays/derivatives, XLA-fusable Horner chain);
- double-double accumulator (for absolute pulse phase, where F0*dt is
  ~1e10 turns and must keep <1e-9 turn error).

Coefficient lists are static Python sequences → the Horner chain unrolls
at trace time into a fixed fused op-chain (no dynamic shapes, MXU/VPU
friendly).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp

from pint_tpu.ops.dd import DD, dd_add, dd_add_f, dd_div_f, dd_mul, _as_dd


def taylor_horner(dt, coeffs: Sequence):
    """Sum_i coeffs[i] * dt^i / i! in plain f64 via Horner."""
    return taylor_horner_deriv(dt, coeffs, deriv_order=0)


def taylor_horner_deriv(dt, coeffs: Sequence, deriv_order: int = 1):
    """deriv_order-th derivative of taylor_horner wrt dt (f64)."""
    coeffs = list(coeffs)
    n = len(coeffs)
    dt = jnp.asarray(dt)
    if dt.dtype not in (jnp.float32, jnp.float64):
        dt = dt.astype(jnp.float64)
    if n <= deriv_order:
        return jnp.zeros_like(dt)
    # derivative shifts the series: result = sum_{i>=d} c_i dt^{i-d}/(i-d)!
    fact = [math.factorial(i - deriv_order) for i in range(deriv_order, n)]
    cs = [float(coeffs[i]) if not hasattr(coeffs[i], "shape") else coeffs[i]
          for i in range(deriv_order, n)]
    acc = jnp.zeros_like(dt)
    for i in reversed(range(len(cs))):
        acc = acc * dt + cs[i] / fact[i]
    return acc


def taylor_powdiff(x, dxy, coeffs: Sequence, t_scale: float = 1.0):
    """Σ_i coeffs[i] · (x^i − y^i)/i!  with  y = x − dxy, computed via
    the exact factorization  x^i − y^i = dxy · Σ_k x^k y^{i−1−k}  so
    the small difference dxy is APPLIED, never recovered by
    subtracting two large powers. This is the anchored delta-phase
    engine: x ~ 1e8 s and the result ~ F·dxy ≤ O(1) turns, yet no
    intermediate carries the ~1e10-turn absolute phase — every term is
    accurate at plain working precision (TPU's emulated f64 included).

    ``t_scale`` normalizes the power sums (Σ (x/T)^k (y/T)^{i-1-k},
    with T^{i-1} folded into the coefficient) so the f32 Jacobian
    path can trace this without overflowing f32 range at high i.
    """
    coeffs = [float(c) for c in coeffs]  # host constants by design:
    # the anchored reference coefficients are fixed at build time, so
    # each c·T^{i-1}/i! is folded in exact host f64 (T^{i-1} would
    # overflow f32 if traced)
    x = jnp.asarray(x)
    if x.dtype not in (jnp.float32, jnp.float64):
        x = x.astype(jnp.float64)
    T = float(t_scale) if t_scale else 1.0
    xs = x / T
    ys = xs - dxy / T
    n = len(coeffs)
    xpow = [jnp.ones_like(xs)]      # xs^0 .. xs^{n-2}
    for _ in range(max(0, n - 2)):
        xpow.append(xpow[-1] * xs)
    total = jnp.zeros_like(x)
    for i in range(1, n):
        if coeffs[i] == 0.0:
            continue
        acc = jnp.zeros_like(x)
        for k in range(i):  # ascending: xs^k added at step k is then
            # multiplied by ys for the remaining i-1-k steps
            acc = acc * ys + xpow[k]
        # acc = Σ_{k=0..i-1} xs^k ys^{i-1-k}
        total = total + (coeffs[i] * T ** (i - 1)
                         / math.factorial(i)) * acc
    return dxy * total


def dd_taylor_horner(dt: DD, coeffs: Sequence) -> DD:
    """Sum_i coeffs[i] * dt^i / i! with a double-double accumulator.

    ``dt`` is DD (seconds since epoch); coeffs are f64 scalars (or DD for
    F0, whose 16 digits alone can't place 1e10 turns to 1e-9 — pass the
    parfile string remainder through a DD coefficient when available).
    """
    n = len(coeffs)
    if n == 0:
        z = jnp.zeros_like(dt.hi)
        return DD(z, z)
    acc = _as_dd(jnp.zeros_like(dt.hi))
    for i in reversed(range(n)):
        ci = coeffs[i]
        fct = float(math.factorial(i))
        acc = dd_mul(acc, dt)
        if isinstance(ci, DD):
            acc = dd_add(acc, dd_div_f(ci, fct) if fct != 1.0 else ci)
        else:
            acc = dd_add_f(acc, jnp.asarray(ci, dt.hi.dtype) / fct)
    return acc
