"""Device-side numeric primitives: double-double arithmetic, Taylor/Horner
evaluation, Chebyshev ephemeris kernels.

These replace the native substrate the reference borrows from numpy
``longdouble`` (x87 80-bit) and scipy — see SURVEY.md §2b.
"""

from pint_tpu.ops.dd import (  # noqa: F401
    DD,
    dd,
    dd_add,
    dd_add_f,
    dd_div,
    dd_frac,
    dd_from_parts,
    dd_mul,
    dd_mul_f,
    dd_neg,
    dd_round,
    dd_sub,
    dd_sub_f,
    dd_to_f64,
    two_sum,
    two_prod,
)
from pint_tpu.ops.taylor import taylor_horner, taylor_horner_deriv, dd_taylor_horner  # noqa: F401
