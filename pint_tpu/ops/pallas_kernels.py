"""Pallas TPU kernels for the photon hot path.

Reference hot spot: src/pint/eventstats.py z2m/hmw evaluate m trig
harmonics over every photon — on Fermi-scale data that is O(1e8)
photons x 20 harmonics of cos/sin plus a weighted reduction, the
dominant cost of photonphase/fermiphase (<N x m> elementwise work
with a tiny output). The XLA path materializes the (m, N) angle
matrix in HBM; this kernel streams (8,128)-shaped photon tiles
through VMEM and accumulates the 2m partial sums in place, so HBM
traffic is exactly one read of phases+weights.

Grid/accumulation pattern per the TPU pallas playbook
(/opt/skills/guides/pallas_guide.md): a 1-D grid over photon tiles,
the (8,128) output block revisited by every step (constant index
map), zero-initialized at step 0 via @pl.when.

f32 by design: pulse phases live in [0,1) and the H statistic needs
~1e-5 relative accuracy; padding rows carry weight 0.

The public entry point falls back to the pure-jnp implementation in
pint_tpu.eventstats off-TPU (or under PINT_TPU_NO_PALLAS=1), and the
interpret-mode test suite checks kernel-vs-jnp agreement without TPU
hardware.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["z2_harmonics_pallas", "pallas_available"]

_TILE_ROWS = 64           # photons per tile = _TILE_ROWS * 128
_LANES = 128


def pallas_available() -> bool:
    # $PINT_TPU_NO_PALLAS through the validated config parser
    # (ISSUE 11 satellite): an unparsable value warns once and keeps
    # the kernels enabled instead of silently disabling them
    from pint_tpu.config import no_pallas

    if no_pallas():
        return False
    return jax.default_backend() == "tpu"


def _harmonics_kernel(m: int, phi_ref, w_ref, out_ref):
    """One photon tile: accumulate the 2m weighted trig sums.

    out_ref is an (8, 128) f32 block revisited by every grid step:
    row 0 holds the m cosine sums, row 1 the m sine sums (lanes >= m
    stay zero)."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    tp = 2.0 * np.float32(np.pi) * phi_ref[:]
    w = w_ref[:]
    # static unroll over harmonics: m <= 20 always (de Jager H-test)
    cos_row = out_ref[0, :]
    sin_row = out_ref[1, :]
    for k in range(1, m + 1):
        ang = np.float32(k) * tp
        c = jnp.sum(w * jnp.cos(ang))
        s = jnp.sum(w * jnp.sin(ang))
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (_LANES,), 0)
                  == (k - 1))
        cos_row = cos_row + jnp.where(onehot, c, 0.0)
        sin_row = sin_row + jnp.where(onehot, s, 0.0)
    out_ref[0, :] = cos_row
    out_ref[1, :] = sin_row


@partial(jax.jit, static_argnames=("m", "interpret"))
def z2_harmonics_pallas(phases, weights, m: int = 20,
                        interpret: bool = False):
    """(cos_sums (m,), sin_sums (m,)) of sum_i w_i e^{2 pi i k phi_i},
    k = 1..m, streamed through VMEM in (64, 128) photon tiles."""
    if pl is None:
        raise ImportError(
            "jax.experimental.pallas is unavailable in this jax "
            "build; use the jnp path (pint_tpu.eventstats)")
    if m > _LANES:
        raise ValueError(
            f"m={m} exceeds the {_LANES}-lane accumulator (the "
            "one-hot scatter would silently drop harmonics)")
    phases = jnp.asarray(phases, dtype=jnp.float32).ravel()
    weights = jnp.asarray(weights, dtype=jnp.float32).ravel()
    n = phases.shape[0]
    tile = _TILE_ROWS * _LANES
    npad = ((n + tile - 1) // tile) * tile
    if npad != n:
        phases = jnp.pad(phases, (0, npad - n))
        weights = jnp.pad(weights, (0, npad - n))  # w=0: inert rows
    rows = npad // _LANES
    phi2 = phases.reshape(rows, _LANES)
    w2 = weights.reshape(rows, _LANES)
    grid = rows // _TILE_ROWS

    out = pl.pallas_call(
        partial(_harmonics_kernel, m),
        out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE_ROWS, _LANES),
                         lambda i: (i, 0)),
            pl.BlockSpec((_TILE_ROWS, _LANES),
                         lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, _LANES), lambda i: (0, 0)),
        interpret=interpret,
    )(phi2, w2)
    return out[0, :m].astype(jnp.float64), \
        out[1, :m].astype(jnp.float64)


# import placed late so the module imports even if pallas is absent
try:  # pragma: no cover - exercised implicitly
    from jax.experimental import pallas as pl
except Exception:  # pallas missing: entry points raise on use
    pl = None  # type: ignore[assignment]
