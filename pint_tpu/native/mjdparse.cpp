// Native host kernel: batch decimal-MJD string -> (day, dd fraction).
//
// The ingestion hot loop (reference analog: the astropy fast C time
// parser behind src/pint/pulsar_mjd.py): a million-TOA tim file parses
// ~30x faster here than in the pure-Python fallback
// (pint_tpu/time/mjd.py parse_mjd_strings, whose double-double
// algorithm this file mirrors operation-for-operation so results are
// bit-identical).
//
// Build (done lazily by pint_tpu.native):
//   g++ -O2 -shared -fPIC -o _mjdparse.so mjdparse.cpp
//
// ABI: plain C, consumed via ctypes.

#include <cstdint>
#include <cstring>

namespace {

struct DD {
  double hi, lo;
};

inline void two_sum(double a, double b, double &s, double &e) {
  s = a + b;
  double bb = s - a;
  e = (a - (s - bb)) + (b - bb);
}

inline void quick_two_sum(double a, double b, double &s, double &e) {
  s = a + b;
  e = b - (s - a);
}

// Dekker split (bit-identical to the numpy mirror, which cannot rely
// on hardware FMA either)
constexpr double SPLITTER = 134217729.0;  // 2^27 + 1

inline void two_prod(double a, double b, double &p, double &e) {
  p = a * b;
  double t = SPLITTER * a;
  double ah = t - (t - a);
  double al = a - ah;
  t = SPLITTER * b;
  double bh = t - (t - b);
  double bl = b - bh;
  e = ((ah * bh - p) + ah * bl + al * bh) + al * bl;
}

inline DD dd_norm(double hi, double lo) {
  double s, e, s2, e2;
  two_sum(hi, lo, s, e);
  quick_two_sum(s, e, s2, e2);
  return {s2, e2};
}

inline DD dd_add(DD a, DD b) {
  double s, e;
  two_sum(a.hi, b.hi, s, e);
  e += a.lo + b.lo;
  double s2, e2;
  quick_two_sum(s, e, s2, e2);
  return {s2, e2};
}

inline DD dd_mul_f(DD a, double b) {
  double p, e;
  two_prod(a.hi, b, p, e);
  double s2, e2;
  quick_two_sum(p, e + a.lo * b, s2, e2);
  return {s2, e2};
}

inline DD dd_div(DD a, DD b) {
  double q1 = a.hi / b.hi;
  DD prod = dd_mul_f(b, q1);
  DD r = dd_add(a, DD{-prod.hi, -prod.lo});
  double q2 = (r.hi + r.lo) / (b.hi + b.lo);
  double s, e;
  quick_two_sum(q1, q2, s, e);
  return {s, e};
}

inline double pow10i(int n) {
  double v = 1.0;
  while (n-- > 0) v *= 10.0;  // exact for n <= 22
  return v;
}

}  // namespace

extern "C" {

// Parse n NUL-terminated decimal MJD strings (concatenated in buf at
// byte offsets offs[i]) into day[i] (exact f64 integer part) and the
// dd fraction (fhi[i], flo[i]). Returns the index of the first bad
// string, or -1 on full success.
long long parse_mjd_batch(const char *buf, const long long *offs,
                          long long n, double *day, double *fhi,
                          double *flo) {
  for (long long i = 0; i < n; ++i) {
    const char *s = buf + offs[i];
    // match python str.strip(): all ASCII whitespace
    auto is_ws = [](char c) {
      return c == ' ' || c == '\t' || c == '\r' || c == '\n' ||
             c == '\f' || c == '\v';
    };
    while (is_ws(*s)) ++s;
    bool neg = false;
    if (*s == '-') {
      neg = true;
      ++s;
    }
    // integer part
    const char *p = s;
    long long ip = 0;
    int ip_digits = 0;
    while (*p >= '0' && *p <= '9') {
      if (ip_digits >= 18) return i;  // next accumulate would overflow
      ip = ip * 10 + (*p - '0');
      ++ip_digits;
      ++p;
    }
    int fp_digits = 0;
    char fp[31];
    if (*p == '.') {
      ++p;
      while (*p >= '0' && *p <= '9' && fp_digits < 30)
        fp[fp_digits++] = *p++;
      while (*p >= '0' && *p <= '9') ++p;  // ignore digits beyond 30
    }
    while (is_ws(*p)) ++p;
    if (*p != '\0' || (ip_digits == 0 && fp_digits == 0)) return i;
    // fraction: front 15 digits / 10^len + back 15 / 10^total — the
    // exact chunking the python mirror uses
    DD frac{0.0, 0.0};
    if (fp_digits > 0) {
      int alen = fp_digits < 15 ? fp_digits : 15;
      long long a = 0;
      for (int k = 0; k < alen; ++k) a = a * 10 + (fp[k] - '0');
      frac = dd_div(dd_norm((double)a, 0.0),
                    dd_norm(pow10i(alen), 0.0));
      if (fp_digits > 15) {
        long long b = 0;
        for (int k = 15; k < fp_digits; ++k) b = b * 10 + (fp[k] - '0');
        // two exact divisors (10^k only exact to k=22) — mirrors the
        // python fallback bit for bit
        DD fb = dd_div(dd_norm((double)b, 0.0),
                       dd_norm(pow10i(fp_digits - 15), 0.0));
        fb = dd_div(fb, dd_norm(pow10i(15), 0.0));
        frac = dd_add(frac, fb);
      }
    }
    day[i] = neg ? -(double)ip : (double)ip;
    if (neg) {
      fhi[i] = -frac.hi;
      flo[i] = -frac.lo;
    } else {
      fhi[i] = frac.hi;
      flo[i] = frac.lo;
    }
  }
  return -1;
}
}
