"""Native (C++) host kernels, loaded via ctypes.

The TPU compute path is JAX/XLA; these accelerate the *host* runtime
around it (the role C extensions play in the reference's dependency
stack — astropy's fast time parser, ERFA). Kernels compile lazily with
g++ on first use and cache the .so next to the source; every native
kernel has a pure-Python twin that produces bit-identical results, so
missing compilers only cost speed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from typing import Optional

import numpy as np

__all__ = ["mjdparse_native", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    src = os.path.join(_DIR, "mjdparse.cpp")
    so = os.path.join(_DIR, "_mjdparse.so")
    if not os.path.exists(so) or \
            os.path.getmtime(so) < os.path.getmtime(src):
        tmp = f"{so}.{os.getpid()}.tmp"
        try:
            # -ffp-contract=off: FMA contraction would break the
            # bit-identical contract with the non-FMA numpy mirror on
            # FMA-default targets (aarch64)
            subprocess.run(
                ["g++", "-O2", "-ffp-contract=off", "-shared",
                 "-fPIC", "-o", tmp, src],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)  # atomic vs concurrent builders
        except (OSError, subprocess.SubprocessError) as e:
            warnings.warn(f"native mjdparse build failed ({e}); "
                          "using the pure-Python parser")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        warnings.warn(f"native mjdparse load failed ({e})")
        return None
    lib.parse_mjd_batch.restype = ctypes.c_longlong
    lib.parse_mjd_batch.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_longlong,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    _LIB = lib
    return lib


def native_available() -> bool:
    return _build_and_load() is not None


def mjdparse_native(strings):
    """Batch-parse decimal MJD strings natively; returns
    (days, (fhi, flo)) or None when the native kernel is unavailable.
    Raises ValueError on a malformed string (same contract as the
    Python parser)."""
    lib = _build_and_load()
    if lib is None:
        return None
    n = len(strings)
    enc = []
    for s in strings:
        if "\x00" in s:
            raise ValueError(f"bad MJD string {s!r}")
        enc.append(s.encode("ascii", "replace"))
    offs = np.empty(n, dtype=np.int64)
    pos = 0
    parts = []
    for i, b in enumerate(enc):
        offs[i] = pos
        parts.append(b)
        pos += len(b) + 1
    buf = b"\x00".join(parts) + b"\x00"
    day = np.empty(n)
    fhi = np.empty(n)
    flo = np.empty(n)
    bad = lib.parse_mjd_batch(buf, offs, n, day, fhi, flo)
    if bad >= 0:
        raise ValueError(f"bad MJD string {strings[bad]!r}")
    return day, (fhi, flo)
