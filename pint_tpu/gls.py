"""Generalized-least-squares fitters (the north-star kernel).

Reference: src/pint/fitter.py (GLSFitter.fit_toas basis/Woodbury branch,
full_cov branch, DownhillGLSFitter). SURVEY.md Appendix A.6 gives the
exact algebra:

    M (N,p)  design matrix, unit-normalized columns, Offset prepended
    F (N,q)  stacked noise bases;  phi (q,) their prior variances
    Nvec     scaled white variances (EFAC/EQUAD applied)
    Sigma = [M|F]^T N^-1 [M|F] + diag(0..0, 1/phi)     ((p+q),(p+q))
    xhat  = Sigma^-1 [M|F]^T N^-1 r
    chi2  = r^T N^-1 r - xhat^T [M|F]^T N^-1 r

The whole solve — whitening, normal-equation assembly, Cholesky,
covariance, chi2, and the GP noise realization F.xhat — is ONE jitted
XLA kernel: the (N,p+q) matmuls tile onto the MXU and dominate the
FLOPs; the (p+q)^2 Cholesky is tiny. An SVD fallback kernel handles
singular systems (the reference's ``threshold`` branch). A dense
full-covariance path (C = N + F phi F^T) is kept as an accuracy
cross-check, as is a pure-numpy mirror of the reference algorithm used
as the benchmark denominator (BASELINE.md measurement protocol).
"""

from __future__ import annotations

import time
import warnings
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitter import Fitter, MaxiterReached
from pint_tpu.residuals import Residuals
from pint_tpu.runtime import DispatchError, get_supervisor

__all__ = ["GLSFitter", "DownhillGLSFitter",
           "DeviceDownhillGLSFitter", "StreamingGLSFitter",
           "gls_solve_np", "NonFiniteStepError"]


class NonFiniteStepError(ValueError):
    """The Cholesky-only device step produced non-finite values
    (singular/degenerate system). Subclasses ValueError for
    backward compatibility; the device fitter catches it to fail
    over to the host fitters' SVD-capable path."""


@partial(jax.jit, static_argnames=("f32mm", "health"))
def _gls_kernel(M, F, phi, r, nvec, f32mm: bool = False,
                health: bool = False):
    """Basis-Woodbury GLS solve. Returns (dparams, cov_pp, chi2,
    noise_resid, xhat_full, ok) — ok False when the Cholesky produced
    non-finite values (caller falls back to SVD). With ``f32mm`` the
    normal-equation matmuls run in f32 at HIGHEST precision (the TPU
    MXU path; see pint_tpu.parallel.fit_step._use_f32_matmul).

    With ``health`` (STATIC, ISSUE 14 — part of the compile key like
    f32mm) a seventh output rides the same dispatch: the in-trace
    health vector ``[nonfinite_count, max_resid_sigma, chi2,
    solve_rel_residual]`` the process ``obs.health.HealthMonitor``
    evaluates host-side. Disarmed, the program is byte-identical to
    the pre-health kernel."""
    p = M.shape[1]
    w = 1.0 / nvec                       # N^-1 diagonal
    # two-stage column scaling: sum(M^2*w) can exceed the exponent
    # range of TPU-emulated f64 (f32-range limited) for F1/F2 columns;
    # dividing by the overflow-safe column max first keeps all
    # intermediates in range (see pint_tpu/parallel/fit_step.py)
    colmax = jnp.max(jnp.abs(M), axis=0)
    colmax = jnp.where(colmax == 0, 1.0, colmax)
    Ms = M / colmax[None, :]
    norm = jnp.sqrt(jnp.sum(Ms * Ms * w[:, None], axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    from pint_tpu.parallel.fit_step import _symm_mm

    big = jnp.concatenate([Mn, F], axis=1)        # (N, p+q)
    sw = jnp.sqrt(w)
    bigs = big * sw[:, None]
    Sigma = _symm_mm(bigs, bigs, f32mm)            # (p+q, p+q)
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi])
    Sigma = Sigma + jnp.diag(prior)
    b = _symm_mm(bigs, (r * sw)[:, None], f32mm)[:, 0]   # (p+q,)
    # Jacobi-preconditioned Cholesky: raw Sigma mixes O(1) data terms
    # with 1/phi priors (~1e25); unit-diagonal scaling keeps the
    # factorization stable, notably on TPU's non-IEEE emulated f64
    d = jnp.sqrt(jnp.diagonal(Sigma))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    cf = jax.scipy.linalg.cho_factor(Sigma / jnp.outer(d, d), lower=True)
    xhat = jax.scipy.linalg.cho_solve(cf, b / d) / d
    inv = jax.scipy.linalg.cho_solve(
        cf, jnp.eye(Sigma.shape[0])) / jnp.outer(d, d)
    chi2 = jnp.sum(r * r * w) - xhat @ b
    dparams = xhat[:p] / colmax / norm
    cov = inv[:p, :p] / jnp.outer(colmax, colmax) / jnp.outer(norm, norm)
    noise_resid = F @ xhat[p:]
    # ok must catch not just non-finites but the finite-garbage case of
    # an (exactly or nearly) singular Sigma, where Cholesky happily
    # produces a huge wrong solution: verify the solve by its relative
    # residual in the preconditioned system
    Sp = Sigma / jnp.outer(d, d)
    solve_err = jnp.linalg.norm(Sp @ (d * xhat) - b / d)
    # 1e-6: backward-stable Cholesky leaves residual ~eps*cond(Sp), so
    # legitimately ill-conditioned-but-solvable systems (cond ~1e8+)
    # must still pass; exact singularity leaves O(1) relative residual
    ok = (jnp.all(jnp.isfinite(xhat)) & jnp.all(jnp.isfinite(cov))
          & (solve_err <= 1e-6 * (jnp.linalg.norm(b / d) + 1.0)))
    if not health:
        return dparams, cov, chi2, noise_resid, xhat, ok
    rel = solve_err / (jnp.linalg.norm(b / d) + 1.0)
    hv = jnp.stack([
        (jnp.sum(~jnp.isfinite(xhat)) + jnp.sum(~jnp.isfinite(chi2))
         ).astype(jnp.float64),
        jnp.max(jnp.abs(r) / jnp.sqrt(nvec)),
        chi2,
        rel,
    ])
    return dparams, cov, chi2, noise_resid, xhat, ok, hv


@partial(jax.jit, static_argnames=("threshold",))
def _gls_kernel_svd(M, F, phi, r, nvec, threshold=1e-12):
    """Eigendecomposition solve of the same normal equations
    (reference: GLSFitter threshold branch, dropping small singular
    values of the scaled design).

    Sigma's raw spectrum is dominated by the 1/phi prior of weakly
    excited noise modes (up to ~1e20 above the O(1) parameter block), so
    a threshold relative to the raw s_max would wrongly discard healthy
    parameter directions. Jacobi-precondition to unit diagonal first:
    genuine degeneracies are then exactly the small eigenvalues."""
    p = M.shape[1]
    w = 1.0 / nvec
    colmax = jnp.max(jnp.abs(M), axis=0)
    colmax = jnp.where(colmax == 0, 1.0, colmax)
    Ms = M / colmax[None, :]
    norm = jnp.sqrt(jnp.sum(Ms * Ms * w[:, None], axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    big = jnp.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi])
    Sigma = Sigma + jnp.diag(prior)
    b = bigw.T @ r
    d = jnp.sqrt(jnp.diagonal(Sigma))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    Sp = Sigma / jnp.outer(d, d)
    s, U = jnp.linalg.eigh(Sp)
    keep = s > threshold * s[-1]
    s_inv = jnp.where(keep, 1.0 / jnp.where(keep, s, 1.0), 0.0)
    xhat = (U @ (s_inv * (U.T @ (b / d)))) / d
    inv = ((U * s_inv[None, :]) @ U.T) / jnp.outer(d, d)
    chi2 = jnp.sum(r * r * w) - xhat @ b
    dparams = xhat[:p] / colmax / norm
    cov = inv[:p, :p] / jnp.outer(colmax, colmax) / jnp.outer(norm, norm)
    noise_resid = F @ xhat[p:]
    return dparams, cov, chi2, noise_resid, xhat


@jax.jit
def _gls_chi2_kernel(F, phi, r, nvec):
    """chi2 at a parameter point: r^T C^-1 r with C = diag(nvec) +
    F diag(phi) F^T, via Woodbury in basis space. Unlike _gls_kernel's
    chi2 (which anticipates the linearized parameter step and is thus
    nearly invariant along it), this is a true function of the current
    parameters — the downhill accept/reject criterion (reference:
    GLSState.chi2 in src/pint/fitter.py)."""
    w = 1.0 / nvec
    bF = (F * w[:, None]).T @ r
    Sff = F.T @ (F * w[:, None]) + jnp.diag(1.0 / phi)
    d = jnp.sqrt(jnp.diagonal(Sff))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    cf = jax.scipy.linalg.cho_factor(Sff / jnp.outer(d, d), lower=True)
    return jnp.sum(r * r * w) - bF @ (
        jax.scipy.linalg.cho_solve(cf, bF / d) / d)


def _gls_chi2_np(F, phi, r, nvec) -> float:
    """Numpy mirror of _gls_chi2_kernel — the supervised dispatch's
    host-failover path (same Woodbury-in-basis-space algebra with
    scipy cho_factor)."""
    from scipy.linalg import cho_factor, cho_solve

    w = 1.0 / nvec
    bF = (F * w[:, None]).T @ r
    Sff = F.T @ (F * w[:, None]) + np.diag(1.0 / phi)
    d = np.sqrt(np.diagonal(Sff)).copy()
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    cf = cho_factor(Sff / np.outer(d, d), lower=True)
    return float(np.sum(r * r * w)
                 - bF @ (cho_solve(cf, bF / d) / d))


def gls_chi2(model, toas, resids=None) -> float:
    """GLS-aware chi2 of current residuals (basis-marginalized)."""
    r = resids if resids is not None else Residuals(toas, model).time_resids
    nvec = model.scaled_toa_uncertainty(toas) ** 2
    F = model.noise_model_designmatrix(toas)
    if F is None:
        return float(np.sum(np.asarray(r) ** 2 / nvec))
    phi = model.noise_model_basis_weight(toas)
    from pint_tpu.config import solve_device, solve_scope

    F_h, phi_h = np.asarray(F), np.asarray(phi)
    r_h, nvec_h = np.asarray(r), np.asarray(nvec)

    def run():
        # placement INSIDE the dispatched closure: H2D to a wedged
        # tunnel hangs like a dispatch, so it rides the watchdog too
        with solve_scope(toas.ntoas):
            return _gls_chi2_kernel(jnp.asarray(F_h), jnp.asarray(phi_h), jnp.asarray(r_h), jnp.asarray(nvec_h))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

    from pint_tpu import obs

    with obs.span("gls.chi2", ntoa=toas.ntoas):
        out = get_supervisor().dispatch(
            run, key="gls.chi2",
            pinned=solve_device(toas.ntoas) is not None,
            fallback=lambda: _gls_chi2_np(F_h, phi_h, r_h, nvec_h))
    return float(out)


@jax.jit
def _gls_kernel_fullcov(M, F, phi, r, nvec):
    """Dense full-covariance GLS (reference: full_cov=True branch):
    C = diag(Nvec) + F diag(phi) F^T, solve via Cholesky of C. O(N^2)
    memory — accuracy cross-check only."""
    C = jnp.diag(nvec) + (F * phi[None, :]) @ F.T
    cf = jax.scipy.linalg.cho_factor(C, lower=True)
    norm = jnp.sqrt(jnp.sum(M * M, axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = M / norm[None, :]
    CiM = jax.scipy.linalg.cho_solve(cf, Mn)
    Cir = jax.scipy.linalg.cho_solve(cf, r)
    Sigma = Mn.T @ CiM
    b = Mn.T @ Cir
    cf2 = jax.scipy.linalg.cho_factor(Sigma, lower=True)
    xhat = jax.scipy.linalg.cho_solve(cf2, b)
    inv = jax.scipy.linalg.cho_solve(cf2, jnp.eye(Sigma.shape[0]))
    chi2 = r @ Cir - xhat @ b
    # conditional mean of the GP: phi F^T C^-1 (r - M dθ) ≈ phi F^T C^-1 r
    noise_resid = (F * phi[None, :]) @ (F.T @ Cir)
    return xhat / norm, inv / jnp.outer(norm, norm), chi2, noise_resid


def gls_solve_np(M, F, phi, r, nvec):
    """Pure-numpy mirror of _gls_kernel — the reference-algorithm CPU
    path used as the benchmark denominator (BASELINE.md protocol; same
    algebra as src/pint/fitter.py GLSFitter.fit_toas with
    scipy cho_factor)."""
    from scipy.linalg import cho_factor, cho_solve

    p = M.shape[1]
    w = 1.0 / nvec
    # identical two-stage equilibration as _gls_kernel (algebraically
    # neutral): column-max scaling keeps sum(M^2*w) in range, and the
    # Jacobi unit-diagonal scaling keeps the Cholesky away from the
    # mixed O(1)-data / 1e25-prior conditioning cliff
    colmax = np.max(np.abs(M), axis=0)
    colmax[colmax == 0] = 1.0
    Ms = M / colmax[None, :]
    norm = np.sqrt(np.sum(Ms * Ms * w[:, None], axis=0))
    norm[norm == 0] = 1.0
    Mn = Ms / norm[None, :]
    big = np.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw + np.diag(
        np.concatenate([np.zeros(p), 1.0 / phi]))
    b = bigw.T @ r
    d = np.sqrt(np.diagonal(Sigma))
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    cf = cho_factor(Sigma / np.outer(d, d), lower=True)
    xhat = cho_solve(cf, b / d) / d
    inv = cho_solve(cf, np.eye(Sigma.shape[0])) / np.outer(d, d)
    chi2 = float(np.sum(r * r * w) - xhat @ b)
    scale = colmax * norm
    return (xhat[:p] / scale, inv[:p, :p] / np.outer(scale, scale), chi2,
            F @ xhat[p:])


def _gls_svd_np(M, F, phi, r, nvec, threshold=1e-12):
    """Pure-numpy mirror of _gls_kernel_svd (Jacobi-preconditioned
    eigh, small-eigenvalue dropping) — the host-failover path for the
    explicit-threshold branch and for degenerate systems where the
    Cholesky mirror raises or produces non-finites."""
    p = M.shape[1]
    w = 1.0 / nvec
    colmax = np.max(np.abs(M), axis=0)
    colmax[colmax == 0] = 1.0
    Ms = M / colmax[None, :]
    norm = np.sqrt(np.sum(Ms * Ms * w[:, None], axis=0))
    norm[norm == 0] = 1.0
    Mn = Ms / norm[None, :]
    big = np.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw + np.diag(
        np.concatenate([np.zeros(p), 1.0 / phi]))
    b = bigw.T @ r
    d = np.sqrt(np.diagonal(Sigma)).copy()
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    Sp = Sigma / np.outer(d, d)
    s, U = np.linalg.eigh(Sp)
    keep = s > threshold * s[-1]
    s_inv = np.where(keep, 1.0 / np.where(keep, s, 1.0), 0.0)
    xhat = (U @ (s_inv * (U.T @ (b / d)))) / d
    inv = ((U * s_inv[None, :]) @ U.T) / np.outer(d, d)
    chi2 = float(np.sum(r * r * w) - xhat @ b)
    scale = colmax * norm
    return (xhat[:p] / scale, inv[:p, :p] / np.outer(scale, scale),
            chi2, F @ xhat[p:])


def _gls_host_failover_solve(M, F, phi, r, nvec, threshold=None,
                             what="normal matrix"):
    """Mode-aware host failover solve (the 'degraded in speed, not
    correctness' contract): honor an explicit SVD threshold; try the
    Cholesky mirror otherwise; degrade to the eigh mirror — with the
    same DegeneracyWarning the device path emits — when the system is
    singular enough that Cholesky raises or returns non-finites. The
    full_cov cross-check mode also lands here: the basis-Woodbury
    mirror is the same algebra by Woodbury identity."""
    if threshold is not None:
        return _gls_svd_np(M, F, phi, r, nvec,
                           threshold=float(threshold))
    try:
        x, cov, chi2, noise = gls_solve_np(M, F, phi, r, nvec)
        if np.all(np.isfinite(x)) and np.isfinite(chi2):
            return x, cov, chi2, noise
    except np.linalg.LinAlgError:
        pass
    from pint_tpu.fitter import warn_degenerate

    warn_degenerate(what)
    return _gls_svd_np(M, F, phi, r, nvec)


class GLSFitter(Fitter):
    """GLS fit with correlated noise marginalized in basis space
    (reference: GLSFitter)."""

    def __init__(self, toas, model, residuals=None, track_mode=None,
                 full_cov=False):
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.full_cov = full_cov
        self.noise_resids: Optional[np.ndarray] = None

    # -- one linearized solve at the current parameters ----------------

    def _solve_once(self, threshold=None):
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        r = self.resids.time_resids
        M, names, units = self.get_designmatrix()
        nvec = self.model.scaled_toa_uncertainty(self.toas) ** 2
        Fb = self.model.noise_model_designmatrix(self.toas)
        phi = self.model.noise_model_basis_weight(self.toas)
        if Fb is None:
            Fb = np.zeros((self.toas.ntoas, 0))
            phi = np.ones(0)
        try:
            return self._solve_once_device(M, Fb, phi, r, nvec,
                                           names, threshold)
        except DispatchError as e:
            # host failover (timed-out / broken / breaker-open
            # backend): the pure-numpy mirror of the same algebra —
            # degraded in speed, not in correctness (mode-aware: the
            # threshold/degenerate route gets the eigh mirror)
            get_supervisor().note_failover("gls.solve", e)
            x, cov, chi2, noise = _gls_host_failover_solve(
                np.asarray(M), np.asarray(Fb), np.asarray(phi),
                np.asarray(r), np.asarray(nvec), threshold=threshold)
            return (-np.asarray(x), np.asarray(cov), float(chi2),
                    np.asarray(noise), names)

    def _solve_once_device(self, M, Fb, phi, r, nvec, names,
                           threshold):
        sup = get_supervisor()
        pinned = self._solve_pinned()
        M_h, Fb_h, phi_h = (np.asarray(M), np.asarray(Fb),
                            np.asarray(phi))
        r_h, nvec_h = np.asarray(r), np.asarray(nvec)

        def place():
            # asarray INSIDE the dispatched closure AND inside the
            # scope: placement follows the pinned device (converting
            # first would ship tiny solves to the accelerator just to
            # pull them back), and an H2D transfer to a wedged tunnel
            # hangs like a dispatch — it must ride the same watchdog
            return (jnp.asarray(M_h), jnp.asarray(Fb_h),
                    jnp.asarray(phi_h), jnp.asarray(r_h),
                    jnp.asarray(nvec_h))

        def run_fullcov():
            with self._solve_scope():
                return _gls_kernel_fullcov(*place())  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        def run_svd(th=None):
            with self._solve_scope():
                if th is None:
                    return _gls_kernel_svd(*place())  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                return _gls_kernel_svd(*place(), threshold=th)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        from pint_tpu import config as _config

        health_on = _config.health_enabled()

        def run_chol(f32mm=False):
            with self._solve_scope():
                return _gls_kernel(*place(), f32mm=f32mm, health=health_on)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        def shadow_chol(out):
            # shadow-oracle replay (ISSUE 14): the numpy mirror of
            # the same algebra; drift = max |d dparams| in sigma of
            # the device covariance. A failed-Cholesky result
            # (ok=False — the DESIGNED degenerate route, about to be
            # SVD-retried by the call site) carries garbage dparams:
            # drifting the mirror against it would be a false
            # numerics verdict, so it is not shadow-applicable.
            if not bool(np.asarray(out[5])):
                return None
            mx, _, _, _ = gls_solve_np(M_h, Fb_h, phi_h, r_h,
                                       nvec_h)
            return _health.drift_sigma(out[0], out[1], mx)

        from pint_tpu import obs
        from pint_tpu.obs import health as _health

        with obs.span("gls.solve_once",
                      fitter=type(self).__name__,
                      ntoa=self.toas.ntoas):
            if self.full_cov:
                x, cov, chi2, noise = sup.dispatch(
                    run_fullcov, key="gls.fullcov", pinned=pinned)
            elif threshold is not None:
                x, cov, chi2, noise, _ = sup.dispatch(
                    run_svd, kw={"th": float(threshold)},
                    key="gls.svd", pinned=pinned)
            else:
                from pint_tpu.parallel.fit_step import _use_f32_matmul

                # when the solve is pinned to the host CPU the
                # f32-MXU auto-on (keyed on the process backend) is
                # moot: CPU f64 is native, so keep full precision
                f32mm = False if pinned else _use_f32_matmul(None)
                out = sup.dispatch(
                    run_chol, kw={"f32mm": f32mm}, key="gls.solve",
                    pinned=pinned, shadow=shadow_chol,
                    shadow_kind="gls")
                x, cov, chi2, noise, _, ok = out[:6]
                hsig = {"values": [x, chi2]}
                if bool(ok):
                    if health_on and len(out) > 6:
                        hsig["hv"] = out[6]
                else:
                    # the DESIGNED degenerate route: warn + SVD
                    # retry. Observed with the FINAL outcome — a
                    # handled fallback that succeeds is not a
                    # numerics incident (the nonfinite check on the
                    # retried values still catches true garbage)
                    from pint_tpu.fitter import warn_degenerate

                    warn_degenerate()
                    x, cov, chi2, noise, _ = sup.dispatch(
                        run_svd, key="gls.svd", pinned=pinned)
                    hsig = {"values": [x, chi2]}
                _health.observe("gls.solve", hsig, key="gls.solve",
                                pool="host" if pinned else "device")
        # r ≈ M (θ − θ_true): the correction is −x (see WLSFitter)
        return (-np.asarray(x), np.asarray(cov), float(chi2),
                np.asarray(noise), names)

    def fit_toas(self, maxiter=1, threshold=None):
        t0 = time.perf_counter()
        for _ in range(max(1, maxiter)):
            x, cov, chi2, noise, names = self._solve_once(threshold)
            self.update_model(x, names)
        # uncertainties, chi2 and noise realization at the final point
        x, cov, chi2, noise, names = self._solve_once(threshold)
        self.set_uncertainties(cov, names)
        self.noise_resids = noise
        self.converged = True
        self._record_stats(chi2, max(1, maxiter), t0)
        return chi2

    def get_noise_resids(self):
        """ML realization of the correlated-noise process [s]
        (reference: GLSFitter resids_noise)."""
        return self.noise_resids


class DownhillGLSFitter(GLSFitter):
    """Step-halving downhill wrapper over the GLS step (reference:
    DownhillGLSFitter)."""

    def _chi2_here(self):
        """chi2 at the current parameter point (basis-marginalized;
        Residuals.chi2 is GLS-aware and does exactly this)."""
        return Residuals(self.toas, self.model,
                         track_mode=self.track_mode).chi2

    def fit_toas(self, maxiter=20, threshold=None, min_lambda=1e-3,
                 required_chi2_decrease=1e-2):
        t0 = time.perf_counter()
        iterations = 0
        best_chi2 = self._chi2_here()
        x = cov = noise = names = None
        converged = False
        for _ in range(maxiter):
            iterations += 1
            x, cov, _, noise, names = self._solve_once(threshold)
            lam, accepted = 1.0, False
            while lam >= min_lambda:
                self.update_model(lam * x, names)
                new_chi2 = self._chi2_here()
                if new_chi2 <= best_chi2 + 1e-12:
                    accepted = True
                    break
                self.update_model(-lam * x, names)
                lam /= 2.0
            if not accepted:
                converged = True
                break
            improved = best_chi2 - new_chi2
            best_chi2 = new_chi2
            self.set_uncertainties(cov, names)
            self.noise_resids = noise
            if improved < required_chi2_decrease:
                converged = True
                break
        else:
            raise MaxiterReached(
                f"no convergence in {maxiter} downhill GLS iterations")
        self.converged = converged
        # refresh uncertainties/noise realization at the final point
        # (_solve_once also leaves self.resids at the final parameters)
        x, cov, _, noise, names = self._solve_once(threshold)
        self.set_uncertainties(cov, names)
        self.noise_resids = noise
        self._record_stats(best_chi2, iterations, t0)
        return best_chi2


class StreamingGLSFitter(GLSFitter):
    """Matrix-free downhill GLS for TOA counts past device memory
    (ISSUE 12): every trial point is ONE streaming pass — the chunked
    normal-equation accumulator of ``parallel.streaming`` (peak
    device memory O(chunk + (p+q)^2), unbounded in N) followed by the
    preconditioned-CG finalize — so the (N, p+q) whitened design is
    never materialized anywhere. ``Fitter.auto`` routes here above
    the ``config.solve_streaming`` TOA threshold
    ($PINT_TPU_STREAM_MIN_TOA).

    Downhill semantics mirror ``DownhillGLSFitter`` (accept iff the
    bases-marginalized chi2 at the trial point improves, halve the
    step to ``min_lambda``, stop below ``required_chi2_decrease``);
    the accept/reject chi2 comes FREE with each accumulation pass
    (it is a scalar of the accumulated state), so a trial costs
    exactly one stream, never a second chi2 pass. Parameter state
    advances host-side in exact dd arithmetic (the device-fitter
    discipline); the model is synced once at the end.

    Degradation contract: a timed-out/broken/breaker-open backend
    fails the WHOLE fit over to the pure-numpy streaming mirror
    (identical algebra, labeled, degraded in speed not correctness);
    a CG/basis-Cholesky failure on the first pass raises
    ``NonFiniteStepError`` — the dense fitters carry the SVD fallback
    the streaming path deliberately lacks."""

    def __init__(self, toas, model, residuals=None, track_mode=None,
                 chunk=None, **step_flags):
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.chunk = chunk
        self.step_flags = dict(step_flags)
        self.cg_iters = None   # CG iterations of the last solve
        self.passes = None     # streaming passes of the last fit
        # solver effort per pass (ISSUE 14 satellite): the CG
        # iteration count and final relative residual of EVERY
        # streaming pass of the last fit, in pass order — the
        # gls_streaming_scan_1m artifact reports these so a 1M-TOA
        # fit's convergence effort is visible, not discarded
        self.cg_iters_per_pass: Optional[list] = None
        self.cg_rel_residual = None  # of the last solve
        self.cg_budget = None        # runtime budget of the solves

    def fit_toas(self, maxiter=20, min_lambda=1e-3,
                 required_chi2_decrease=1e-2, cg_tol=1e-13):
        from pint_tpu import obs

        t0 = time.perf_counter()
        self.passes = None
        try:
            with obs.span("fit.streaming", ntoa=self.toas.ntoas,
                          maxiter=maxiter):
                return self._fit_stream(maxiter, min_lambda,
                                        required_chi2_decrease,
                                        cg_tol, t0)
        except DispatchError as e:
            get_supervisor().note_failover("gls.stream_fit", e)
            with obs.span("fit.stream_host_failover",
                          cause=f"{type(e).__name__}: {e}"):
                return self._fit_host_mirror(
                    maxiter, min_lambda, required_chi2_decrease,
                    cg_tol, e, t0)

    def _fit_stream(self, maxiter, min_lambda,
                    required_chi2_decrease, cg_tol, t0):
        from pint_tpu.ops import dd_np
        from pint_tpu.parallel.streaming import StreamingGLS

        sg = StreamingGLS(self.model, self.toas, chunk=self.chunk,
                          **self.step_flags)
        names = sg.names
        noff = 1 if names and names[0] == "Offset" else 0
        th, tl = sg.th0.copy(), sg.tl0.copy()

        def bump(th_, tl_, d):
            s = dd_np.add(dd_np.dd(th_, tl_), dd_np.dd(d))
            return np.asarray(s[0]), np.asarray(s[1])

        effort: list = []   # (cg_iters, rel_resid) per pass
        self.cg_budget = sg.default_budget

        def one_pass(th_, tl_, observe=True):
            # trial passes suppress the per-pass health observation
            # (a rejected line-search overshoot is the damping
            # working, not an incident — the build_fit_loop hv
            # discipline); ACCEPTED trials are observed below
            state = sg.accumulate(th_, tl_, observe=observe)
            out = sg.solve(state, tol=cg_tol, observe=observe)
            effort.append((int(out[6]), float(out[7])))
            return out

        def observe_accepted(out):
            from pint_tpu.obs import health as _health

            sig = {"cg_iters": int(out[6]),
                   "cg_budget": int(self.cg_budget),
                   "cg_rel_residual": float(out[7]),
                   "ok": bool(out[5]), "chi2": float(out[3]),
                   "values": [out[0], out[2]]}
            hv = sg.last_pass_hv
            if hv is not None:
                # the accepted pass's ACCUMULATE taps too (nonfinite
                # Sig/b, colmax rescale) — suppressed per-trial
                # above, owed for the state the fit actually keeps
                sig["nonfinite"] = hv[0]
                sig["rescale"] = hv[1]
            _health.observe("stream.solve", sig,
                            key="stream.solve")

        dp, cov, _, best, xf, ok, iters, rel = one_pass(th, tl)
        npass = 1
        if not ok or not np.all(np.isfinite(dp)):
            raise NonFiniteStepError(
                "streaming CG solve failed (singular/degenerate "
                "system?); use GLSFitter's SVD fallback")
        iterations = 0
        converged = False
        maxed_out = False
        for _ in range(maxiter):
            iterations += 1
            d = dp[noff:]
            lam, accepted = 1.0, False
            while lam >= min_lambda:
                thc, tlc = bump(th, tl, lam * d)
                outc = one_pass(thc, tlc, observe=False)
                dpc, covc, _, chic, xfc, okc, iters, rel = outc
                npass += 1
                if okc and np.isfinite(chic) and \
                        chic <= best + 1e-12:
                    accepted = True
                    observe_accepted(outc)
                    break
                lam /= 2.0
            if not accepted:
                converged = True
                break
            improved = best - chic
            th, tl = thc, tlc
            dp, cov, best, xf = dpc, covc, chic, xfc
            if improved < required_chi2_decrease:
                converged = True
                break
        else:
            maxed_out = True
        self.cg_iters = int(iters)
        self.cg_rel_residual = float(rel)
        self.cg_iters_per_pass = [it for it, _ in effort]
        self.passes = npass
        # sync the model to the accepted point (dd-exact difference
        # vs the build slots, the device-fitter convention)
        total = dd_np.sub(dd_np.dd(th, tl), dd_np.dd(sg.th0, sg.tl0))
        delta_f64 = dd_np.to_f64(total)
        self.update_model(
            np.concatenate([np.zeros(noff), delta_f64]), names)
        self.set_uncertainties(cov, names)
        self.noise_resids = sg.noise_realization(xf)
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        self.converged = converged
        self._record_stats(best, max(1, iterations), t0)
        if maxed_out:
            raise MaxiterReached(
                f"no convergence in {maxiter} streaming downhill "
                f"iterations (model left at the best point found)")
        return best

    def _fit_host_mirror(self, maxiter, min_lambda,
                         required_chi2_decrease, cg_tol, cause, t0):
        """Degraded-but-correct: the identical downhill loop through
        the pure-numpy streaming mirror (host design-matrix assembly
        + chunked numpy accumulate + numpy CG), with the model synced
        before every trial pass — labeled, never silent."""
        import warnings as _warnings

        from pint_tpu.parallel.streaming import StreamingGLS

        _warnings.warn(
            f"streaming device fit unavailable ({type(cause).__name__}"
            f": {cause}); failed over to the numpy streaming mirror",
            RuntimeWarning, stacklevel=3)
        sg = StreamingGLS(self.model, self.toas, chunk=self.chunk,
                          **self.step_flags)
        names = sg.names
        noff = 1 if names and names[0] == "Offset" else 0
        effort: list = []
        self.cg_budget = sg.default_budget

        def one_pass():
            out = sg.solve_np(tol=cg_tol)
            effort.append((int(out[6]), float(out[7])))
            return out

        def apply(x, sign=1.0):
            self.update_model(sign * np.concatenate(
                [np.zeros(noff), x]), names)

        dp, cov, _, best, xf, ok, iters, rel = one_pass()
        if not ok or not np.all(np.isfinite(dp)):
            raise NonFiniteStepError(
                "streaming host-mirror solve failed (singular/"
                "degenerate system?)")
        iterations = 0
        converged = False
        maxed_out = False
        for _ in range(maxiter):
            iterations += 1
            d = np.asarray(dp[noff:], np.float64)
            lam, accepted = 1.0, False
            while lam >= min_lambda:
                apply(lam * d)
                dpc, covc, _, chic, xfc, okc, iters, rel = \
                    one_pass()
                if okc and np.isfinite(chic) and \
                        chic <= best + 1e-12:
                    accepted = True
                    break
                apply(lam * d, sign=-1.0)
                lam /= 2.0
            if not accepted:
                converged = True
                break
            improved = best - chic
            dp, cov, best, xf = dpc, covc, chic, xfc
            if improved < required_chi2_decrease:
                converged = True
                break
        else:
            maxed_out = True
        self.cg_iters = int(iters)
        self.cg_rel_residual = float(rel)
        self.cg_iters_per_pass = [it for it, _ in effort]
        self.set_uncertainties(cov, names)
        self.noise_resids = sg.noise_realization(xf)
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        self.converged = converged
        self._record_stats(best, max(1, iterations), t0)
        if maxed_out:
            raise MaxiterReached(
                f"no convergence in {maxiter} streaming downhill "
                f"iterations (host mirror)")
        return best


class DeviceDownhillGLSFitter(GLSFitter):
    """Downhill GLS where EVERY trial iteration is the one-kernel
    jitted fit step (pint_tpu.parallel.build_fit_step): phase, design
    matrix, whitening, ECORR downdates, normal equations, Cholesky and
    the accept/reject chi2 all stay device-resident — one device
    round-trip per trial instead of the host fitter's
    residuals/designmatrix/solve phases. Parameter state advances on
    the HOST in exact arithmetic: in anchored mode as the cumulative
    dd delta against the build anchor (the step's (th, tl) slots), in
    direct mode as compensated updates of the packed dd pairs.

    Composes with every step flag (anchored / jac_f32 / matmul_f32 /
    wideband) — on TPU the production configuration is auto-on, making
    this the fastest full-fit path on the hardware the framework is
    named for. Singular systems are the caller's concern (the step is
    Cholesky-only): a non-finite first step raises instead of silently
    falling back.

    Dispatch-tax killers (ISSUE 7): ``whole_fit`` runs the ENTIRE
    downhill fit — damping, acceptance, convergence — as ONE
    deadline-supervised ``lax.while_loop`` dispatch (the K-chained
    loop with maxiter as a runtime budget; auto-on on accelerator
    backends via config.whole_fit_enabled); the loop's (th, tl)
    parameter state is DONATED (config.donation_enabled) so the
    iterated pair aliases in place instead of round-tripping HBM; and
    ``pipeline`` overlaps multi-chunk fits by issuing the next chunk
    from the device-advanced pair while the host replays the ledger
    (supervisor pipeline mode, depth-scaled watchdog deadline)."""

    def __init__(self, toas, model, residuals=None, track_mode=None,
                 wideband=False, whole_fit=None, pipeline=None,
                 **step_flags):
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.wideband = wideband
        self.whole_fit = whole_fit
        self.pipeline = pipeline
        self.step_flags = dict(step_flags, wideband=wideband)
        self.step_evals = None   # step_fn evaluations of the last fit

    def fit_toas(self, maxiter=20, min_lambda=1e-3,
                 required_chi2_decrease=1e-2,
                 steps_per_dispatch=None, whole_fit=None,
                 pipeline=None):
        """``steps_per_dispatch`` > 1 runs that many downhill
        iterations inside ONE device program (build_fit_loop) and
        replays the returned ledger on host in exact dd — measured on
        the axon tunnel every dispatch carries a large fixed cost, so
        this is the difference between a usable and an unusable
        full-fit path on TPU. Default: adaptive — sized from the
        measured dispatch RTT (config.auto_steps_per_dispatch: 1 on
        CPU, ~4-8 on a local chip, 16-32 over the high-latency axon
        tunnel); the chained loop early-exits on in-kernel convergence
        so oversizing K wastes no iterations.

        ``whole_fit`` (ISSUE 7 tentpole) makes the ENTIRE downhill
        fit — damping, acceptance, convergence — ONE deadline-
        supervised dispatch: the compiled-loop K is the smallest
        power of two covering ``maxiter`` (same quantized compile
        keys as the adaptive chaining — whole-fit is the K=inf case
        of the same program) and ``maxiter`` rides along as the
        RUNTIME iteration budget, so no fresh compile per distinct
        maxiter and no iteration past it. Default: explicit argument
        > constructor flag > ``config.whole_fit_enabled()`` (auto-on
        on accelerator backends, $PINT_TPU_WHOLE_FIT). An explicit
        ``steps_per_dispatch`` wins over whole-fit. With
        ``config.donation_enabled()`` the loop's (th, tl) parameter
        state is donated (donate_argnums) so the iterated pair stops
        round-tripping HBM each dispatch.

        ``pipeline`` (default: on off-CPU backends) overlaps the
        multi-dispatch case: the next chunk is issued asynchronously
        from the device-advanced (th', tl') pair — bit-identical to
        the host ledger replay on IEEE hardware (see
        build_fit_loop's precision contract) — while the host
        replays the ledger of the chunk just read, and the
        supervisor's watchdog deadline covers the in-flight window.
        On TPU's non-IEEE emulated f64 the device pair differs from
        the host replay by <=2^-48 of the (anchored) delta — the
        SAME bound the in-kernel two-sum advance already carries
        inside every chunk, so pipelining adds no new error class:
        accept/reject decisions can differ only within that noise
        floor, and the final model state always comes from the exact
        host ledger replay either way. A whole fit that converges
        inside one dispatch never pipelines (there is nothing in
        flight to overlap).

        Every device dispatch runs under the runtime supervisor's
        watchdog deadline; an unresponsive/broken backend (or a
        non-finite first step — the host fitters carry the SVD
        fallback the device step lacks) fails the WHOLE fit over to
        the host downhill fitter. The model is only ever mutated
        after a completed dispatch loop, so the failover starts from
        the pre-fit state and its result is bit-identical to running
        the host fitter directly."""
        from pint_tpu import obs

        t0 = time.perf_counter()
        # reset BEFORE the attempt: after a host failover the count
        # must read None (no device evals ran), not the previous
        # fit's number — unlabeled degradation is the failure mode
        # the runtime layer exists to prevent
        self.step_evals = None
        # the whole fit is one trace (ISSUE 10): every chained-loop
        # dispatch, pipelined chunk issue and supervisor child event
        # (retry/timeout/failover) parents under this span, and a
        # host failover is a labeled sibling — the causal story
        # behind a DEGRADED artifact
        try:
            with obs.span("fit.device", fitter=type(self).__name__,
                          ntoa=self.toas.ntoas, maxiter=maxiter):
                return self._fit_device(maxiter, min_lambda,
                                        required_chi2_decrease,
                                        steps_per_dispatch, t0,
                                        whole_fit=whole_fit,
                                        pipeline=pipeline)
        except (DispatchError, NonFiniteStepError) as e:
            get_supervisor().note_failover("gls.device_fit", e)
            with obs.span("fit.host_failover",
                          cause=f"{type(e).__name__}: {e}"):
                return self._fit_host_failover(
                    maxiter, min_lambda, required_chi2_decrease, e,
                    t0)

    def _fit_host_failover(self, maxiter, min_lambda,
                           required_chi2_decrease, cause, t0):
        """Degraded-but-correct: rerun the fit through the host
        downhill fitter (CPU-pinned exact-dd surfaces + SVD-capable
        solve) and adopt its fitted state wholesale."""
        import warnings as _warnings

        if self.wideband:
            from pint_tpu.wideband_fitter import WidebandDownhillFitter

            host = WidebandDownhillFitter(self.toas, self.model,
                                          track_mode=self.track_mode)
        else:
            host = DownhillGLSFitter(self.toas, self.model,
                                     track_mode=self.track_mode)
        _warnings.warn(
            f"device fit unavailable ({type(cause).__name__}: "
            f"{cause}); failed over to {type(host).__name__}",
            RuntimeWarning, stacklevel=3)
        chi2 = host.fit_toas(
            maxiter=maxiter, min_lambda=min_lambda,
            required_chi2_decrease=required_chi2_decrease)
        self.resids = host.resids
        self.errors = host.errors
        self.parameter_covariance_matrix = \
            host.parameter_covariance_matrix
        self.noise_resids = host.noise_resids
        if self.wideband:
            self.dm_resids = host.dm_resids
        self.converged = host.converged
        self.stats = host.stats
        if self.stats is not None:
            # label the TRUE degraded latency: the wall must include
            # the watchdog deadline burned before failover, not just
            # the host rerun (degraded runs are labeled, never
            # silently slow)
            full_wall = time.perf_counter() - t0
            self.stats.wall_time_s = full_wall
            self.stats.toas_per_sec = (
                self.stats.ntoa * max(1, self.stats.iterations)
                / full_wall if full_wall else 0.0)
        return chi2

    def _fit_device(self, maxiter, min_lambda,
                    required_chi2_decrease, steps_per_dispatch, t0,
                    whole_fit=None, pipeline=None):
        from pint_tpu import config
        from pint_tpu.config import auto_steps_per_dispatch
        from pint_tpu.ops import dd_np
        from pint_tpu.parallel import build_fit_loop, build_fit_step

        whole = config.whole_fit_enabled(
            whole_fit if whole_fit is not None else self.whole_fit)
        if steps_per_dispatch is None:
            if whole:
                # whole-fit-on-device: K = the smallest power of two
                # covering maxiter, from the SAME quantized set as
                # the adaptive chaining ({4,8,16,32},
                # config.auto_steps_per_dispatch) so whole-fit reuses
                # the chained executables — chaining is just the
                # small-budget case of this one program. maxiter
                # itself rides along as the runtime iteration budget
                # (build_fit_loop), so maxiter > 32 degrades to
                # chained dispatches of 32 rather than a fresh
                # compile key.
                k = 4
                while k < maxiter and k < 32:
                    k *= 2
                steps_per_dispatch = k
            else:
                steps_per_dispatch = auto_steps_per_dispatch()
        if pipeline is None:
            pipeline = self.pipeline
        if pipeline is None:
            pipeline = jax.default_backend() != "cpu"
        sup = get_supervisor()

        def bump(th_, tl_, d):
            """(th, tl) + d with the low part carrying the rounding
            remainder — the delta survives exactly (dd discipline)."""
            s = dd_np.add(dd_np.dd(th_, tl_), dd_np.dd(d))
            return np.asarray(s[0]), np.asarray(s[1])

        def nonfinite_error():
            raise NonFiniteStepError(
                "device fit step produced non-finite values "
                "(singular system? use GLSFitter's SVD fallback)")

        if steps_per_dispatch > 1:
            # maxiter is honored EXACTLY: the loop program is
            # compiled for the fixed (quantized) K but takes the
            # remaining iteration allowance as a runtime budget
            # argument, so neither a fresh compile per distinct
            # maxiter nor an overshoot past it
            loop_fn, args, names = build_fit_loop(
                self.model, self.toas,
                max_iter=int(steps_per_dispatch),
                min_lambda=min_lambda,
                required_chi2_decrease=required_chi2_decrease,
                **self.step_flags)
            donated = config.donation_enabled()
            if donated:
                # the iterated (th, tl) pair aliases the loop's
                # (th', tl') outputs exactly — donated, the
                # parameter state stops round-tripping HBM on every
                # dispatch (the run closure rebuilds fresh device
                # arrays from host numpy each call, so no caller
                # ever reads a donated buffer; graftlint G11 guards
                # the pattern)
                jitted = jax.jit(loop_fn, donate_argnums=(0, 1))
            else:
                jitted = jax.jit(loop_fn)
        else:
            loop_fn, args, names = build_fit_step(
                self.model, self.toas, **self.step_flags)
            jitted = jax.jit(loop_fn)
        noff = 1 if names and names[0] == "Offset" else 0
        # host-side exact parameter state in the step's (th, tl) slots
        th = np.asarray(args[0], np.float64).copy()
        tl = np.asarray(args[1], np.float64).copy()
        iterations = 0
        nevals = 0
        converged = False
        maxed_out = False
        chained_k = int(steps_per_dispatch)

        if steps_per_dispatch > 1:
            body = args[2:-1]   # args[-1] is the default budget

            def run(th_, tl_, budget_):
                """One supervised device dispatch of the chained
                loop. Executed on the supervisor's guarded worker;
                the host reads happen INSIDE so the watchdog
                deadline covers completion — over the axon tunnel
                the dispatch ack only confirms enqueue."""
                out = jitted(jnp.asarray(th_), jnp.asarray(tl_), *body, jnp.asarray(int(budget_), jnp.int32))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                hs = [np.asarray(o) for o in out]
                if donated:
                    # OWNED arrays, not views: with donation the
                    # loop's (th', tl') outputs alias the donated
                    # input buffers, and a zero-copy view escaping
                    # the closure would dangle once XLA reuses the
                    # memory (the runtime counterpart of G11). Copy
                    # only actual views — an accelerator D2H read is
                    # already a fresh owned buffer.
                    hs = [h if h.flags.owndata else h.copy()
                          for h in hs]
                return hs
        else:
            rest = args[2:]

            def run(th_, tl_):
                """One supervised device dispatch (see above; the
                single-step path never donates)."""
                out = jitted(jnp.asarray(th_), jnp.asarray(tl_), *rest)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                return [np.asarray(o) for o in out]

        from pint_tpu.obs import health as _health

        if steps_per_dispatch > 1:
            budget = int(min(chained_k, maxiter))
            handle = None
            while True:
                if handle is not None:
                    out = handle.result()
                    handle = None
                else:
                    out = sup.dispatch(run, th, tl, budget,
                                       key="gls.fit_loop",
                                       steps=budget)
                dp = np.asarray(out[2], np.float64)
                cov = np.asarray(out[3])
                best = float(out[4])
                # health tap (ISSUE 14): the loop's in-trace vector
                # (accepted-state non-finite count / max whitened
                # residual / chi2) when armed, plus the returned
                # host scalars either way — observed BEFORE the
                # non-finite guard below so an injected-NaN readback
                # is an incident AND the failover story is unchanged
                hsig = {"values": [dp, out[4]], "chi2": best,
                        "chi2_prev": float(out[5])}
                if len(out) > 11:
                    hsig["hv"] = out[11]
                _health.observe("fit.device", hsig,
                                key="gls.fit_loop")
                if iterations == 0 and (
                        not np.isfinite(float(out[5]))
                        or not np.all(np.isfinite(dp))):
                    nonfinite_error()
                niter = int(out[6])
                deltas = np.asarray(out[8], np.float64)
                lams = np.asarray(out[9], np.float64)
                nevals += int(out[10])
                done_dev = bool(out[7])   # loop converged on device
                will_continue = (not done_dev
                                 and iterations + niter < maxiter)
                if will_continue:
                    budget = int(min(chained_k,
                                     maxiter - iterations - niter))
                    if pipeline:
                        # pipelined chaining: issue the next chunk
                        # NOW from the device-advanced (th', tl')
                        # pair — bit-identical to the ledger replay
                        # below on IEEE hardware (build_fit_loop's
                        # precision contract: the in-kernel two-sum
                        # and the host dd replay are 1:1 mirrors) —
                        # so the exact host replay overlaps the
                        # in-flight dispatch instead of serializing
                        # with it
                        handle = sup.dispatch_async(
                            run, np.asarray(out[0], np.float64),
                            np.asarray(out[1], np.float64), budget,
                            key="gls.fit_loop", steps=budget)
                # exact host replay of the device's accepted updates
                for k in range(niter):
                    if lams[k] > 0.0:
                        th, tl = bump(th, tl, deltas[k])
                iterations += niter
                if done_dev:
                    converged = True
                    break
                if iterations >= maxiter:
                    maxed_out = True
                    break
        else:
            out = sup.dispatch(run, th, tl, key="gls.fit_step")
            nevals += 1
            dp = np.asarray(out[0], np.float64)
            cov = np.asarray(out[1])
            best = float(out[2])
            hsig = {"values": [dp, out[2]], "chi2": best}
            if len(out) > 4:
                hsig["hv"] = out[4]
            _health.observe("fit.device", hsig, key="gls.fit_step")
            if not np.isfinite(best) or not np.all(np.isfinite(dp)):
                nonfinite_error()
            for _ in range(maxiter):
                iterations += 1
                lam, accepted = 1.0, False
                while lam >= min_lambda:
                    thc, tlc = bump(th, tl, lam * dp[noff:])
                    outc = sup.dispatch(run, thc, tlc,
                                        key="gls.fit_step")
                    nevals += 1
                    newchi2 = float(outc[2])
                    if np.isfinite(newchi2) and \
                            newchi2 <= best + 1e-12:
                        accepted = True
                        break
                    lam /= 2.0
                if not accepted:
                    converged = True
                    break
                # the ACCEPTED step's health tap (rejected trials
                # are the damping working — the build_fit_loop hv
                # discipline; this mirrors the chained path's
                # accepted-state observation)
                hsig = {"values": [outc[0], outc[2]],
                        "chi2": newchi2, "chi2_prev": best}
                if len(outc) > 4:
                    hsig["hv"] = outc[4]
                _health.observe("fit.device", hsig,
                                key="gls.fit_step")
                improved = best - newchi2
                th, tl = thc, tlc
                dp = np.asarray(outc[0], np.float64)
                cov = np.asarray(outc[1])
                best = newchi2
                if improved < required_chi2_decrease:
                    converged = True
                    break
            else:
                maxed_out = True
        self.step_evals = nevals
        # sync the model to the accepted device state even when about
        # to raise: callers catching MaxiterReached expect the best
        # point found (host DownhillGLSFitter behavior). (th, tl) are
        # deltas vs the zeroed build slots in anchored mode and full
        # pairs otherwise — the difference formula covers both.
        th0 = np.asarray(args[0], np.float64)
        tl0 = np.asarray(args[1], np.float64)
        total = dd_np.sub(dd_np.dd(th, tl), dd_np.dd(th0, tl0))
        delta_f64 = dd_np.to_f64(total)
        self.update_model(
            np.concatenate([np.zeros(noff), delta_f64]), names)
        self.set_uncertainties(cov, names)
        # degeneracy detector: at a genuine optimum the final
        # proposed GLS correction is <~1 sigma of its own reported
        # uncertainty. "Converged" with a HUGE proposed-but-rejected
        # step means the quadratic model and the chi2 surface
        # disagree — the Cholesky-only device solve produced a
        # non-descent direction, which is what a (near-)singular
        # design does (measured failure: an FD/FDJUMP model with only
        # two distinct frequencies stalls at chi2/dof ~2-6 while the
        # host SVD-capable fitters reach ~1). Warn and point at the
        # fallback rather than silently reporting the stall as a fit.
        with np.errstate(invalid="ignore", divide="ignore"):
            sig_steps = np.abs(np.asarray(dp, np.float64)) / \
                np.sqrt(np.abs(np.diagonal(np.asarray(cov))))
        # non-finite entries ARE the most degenerate outcome (a NaN
        # step after the first dispatch passes the entry guard): flag
        # them instead of letting nanmax swallow them silently
        bad = bool(sig_steps.size) and \
            not np.all(np.isfinite(sig_steps))
        finite = sig_steps[np.isfinite(sig_steps)]
        worst = float(finite.max()) if finite.size else 0.0
        if converged and (bad or worst > 1e3):
            warnings.warn(
                f"device downhill converged but the last proposed "
                f"correction is "
                f"{'non-finite' if bad else f'{worst:.1e} sigma'} — "
                f"the system is likely singular/degenerate (collinear "
                f"design columns?); prefer GLSFitter/"
                f"DownhillGLSFitter (SVD fallback) for this model",
                RuntimeWarning, stacklevel=2)
        # final host refresh at the accepted optimum: residuals and
        # the ML noise realization (the device step returns neither
        # the basis amplitudes nor DM residuals)
        if self.wideband:
            from pint_tpu.wideband_fitter import WidebandTOAFitter

            helper = WidebandTOAFitter(self.toas, self.model)
            _, _, _, noise, _ = helper._solve_once()
            self.noise_resids = noise
            self.resids = helper.resids
            self.dm_resids = helper.dm_resids
            dof = helper._wb_dof()
        else:
            _, _, _, noise, _ = self._solve_once()
            self.noise_resids = noise
            dof = None
        self.converged = converged
        self._record_stats(best, iterations, t0, dof=dof)
        if maxed_out:
            raise MaxiterReached(
                f"no convergence in {maxiter} device downhill "
                f"iterations (model left at the best point found)")
        return best
