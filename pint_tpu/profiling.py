"""Profiling & metrics instrumentation (SURVEY.md §5: tracing and a
TOAs/sec scoreboard are first-class requirements; the reference has no
equivalent — loguru DEBUG lines in src/pint/toa.py / fitter.py are its
only visibility).

Two layers:

- ``FitStats``: the structured per-fit stats object every fitter
  returns/attaches (chi2, iterations, wall time, TOAs/sec).
- ``trace``/``annotate``: thin wrappers over ``jax.profiler`` so a fit
  can be decomposed (phase chain vs jacfwd vs Cholesky) with
  tensorboard-compatible traces, plus a process-wide scoreboard of
  named wall-clock phases for quick attribution without a trace viewer.
"""

from __future__ import annotations

import contextlib
import json
from pint_tpu.runtime import locks
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional

__all__ = ["FitStats", "trace", "annotate", "scoreboard", "Scoreboard"]


@dataclass
class FitStats:
    """Structured result of one fit (returned via Fitter.stats)."""

    fitter: str = ""
    ntoa: int = 0
    nfree: int = 0
    dof: int = 0
    chi2: float = float("nan")
    reduced_chi2: float = float("nan")
    iterations: int = 0
    converged: bool = False
    wall_time_s: float = 0.0
    toas_per_sec: float = 0.0
    phases: Dict[str, float] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    def __str__(self) -> str:
        return (f"{self.fitter}: chi2={self.chi2:.3f} "
                f"(red. {self.reduced_chi2:.4f}), "
                f"{self.iterations} iter in {self.wall_time_s * 1e3:.1f} ms "
                f"({self.toas_per_sec:.0f} TOA/s)")


class Scoreboard:
    """Accumulates named wall-clock phases; the cheap always-on half of
    the profiling story (the expensive half is jax.profiler traces).

    ISSUE 15: the phase rows are REGISTRY-BACKED — each phase holds a
    shared ``obs.metrics`` histogram row
    (``pint_tpu_scoreboard_seconds{scope, phase}``, the ISSUE-11
    ``row_factory`` pattern), so ``annotate()`` regions appear in
    ``/metrics`` and serve snapshots instead of a report-only dict.
    ``totals``/``counts`` are derived views of the SAME rows (the
    registry-vs-snapshot parity discipline); ``obs.reset()`` clears
    the scoreboard with the registry it was bound to."""

    def __init__(self):
        self._lock = locks.make_lock("profiling.scoreboard")
        self._rows: Dict[str, object] = {}
        self._scope: Optional[str] = None

    def _row(self, name: str):
        row = self._rows.get(name)
        if row is None:
            from pint_tpu.obs import metrics as om

            with self._lock:
                row = self._rows.get(name)
                if row is None:
                    if self._scope is None:
                        # per-instance scope: two scoreboards (the
                        # global one, a test's) must never share rows
                        self._scope = om.new_scope("sb")
                    row = om.histogram(
                        "pint_tpu_scoreboard_seconds",
                        "annotate()/phase wall per named region"
                    ).row(scope=self._scope, phase=name)
                    self._rows[name] = row
        return row

    @contextlib.contextmanager
    def phase(self, name: str):
        row = self._row(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            row.record(time.perf_counter() - t0)

    # -- derived views (the pre-ISSUE-15 attribute surface) ------------

    @property
    def totals(self) -> Dict[str, float]:
        with self._lock:
            rows = dict(self._rows)
        return {k: r.sum_s for k, r in rows.items() if r.count}

    @property
    def counts(self) -> Dict[str, int]:
        with self._lock:
            rows = dict(self._rows)
        return {k: r.count for k, r in rows.items() if r.count}

    def snapshot(self) -> dict:
        """{phase: histogram snapshot} — the serve-snapshot block."""
        with self._lock:
            rows = dict(self._rows)
        return {k: r.snapshot() for k, r in sorted(rows.items())
                if r.count}

    def report(self) -> str:
        totals, counts = self.totals, self.counts
        lines = [f"{'phase':<28} {'total_s':>10} {'calls':>7} {'avg_ms':>10}"]
        for k in sorted(totals, key=totals.get, reverse=True):
            t, c = totals[k], counts[k]
            lines.append(f"{k:<28} {t:>10.3f} {c:>7} {t / c * 1e3:>10.2f}")
        return "\n".join(lines)

    def reset(self):
        """Drop the rows (obs.reset calls this: the registry they
        were bound to was just swapped — fresh phases register
        fresh rows, stale rows stop being visible anywhere)."""
        with self._lock:
            self._rows.clear()


scoreboard = Scoreboard()


@contextlib.contextmanager
def trace(logdir: Optional[str] = None):
    """Capture a jax.profiler device trace around a block (view with
    tensorboard / xprof). No-op when logdir is None.

    This is the UNMANAGED form for scripts that own their own
    lifetime (bench attribution runs). Production code wants
    ``pint_tpu.obs.perf.request_window`` instead: supervised,
    bounded ($PINT_TPU_PROFILE_MAX_S), rate-limited, hang-proof
    stop, cross-linked window metadata — and auto-fired on
    slo_burn/breaker-open incidents (ISSUE 15). graftlint G15 keeps
    raw ``jax.profiler.start_trace`` calls confined to these two
    modules."""
    if logdir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region: shows up inside device traces, feeds the
    scoreboard, AND (ISSUE 10) opens a tracer span under the current
    causal context — ONE instrumentation point serves jax.profiler,
    the process scoreboard and the structured trace. With tracing
    off the span is the shared no-op."""
    import jax

    from pint_tpu import obs

    with jax.profiler.TraceAnnotation(name), scoreboard.phase(name), \
            obs.span(name, kind="annotate"):
        yield
