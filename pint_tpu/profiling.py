"""Profiling & metrics instrumentation (SURVEY.md §5: tracing and a
TOAs/sec scoreboard are first-class requirements; the reference has no
equivalent — loguru DEBUG lines in src/pint/toa.py / fitter.py are its
only visibility).

Two layers:

- ``FitStats``: the structured per-fit stats object every fitter
  returns/attaches (chi2, iterations, wall time, TOAs/sec).
- ``trace``/``annotate``: thin wrappers over ``jax.profiler`` so a fit
  can be decomposed (phase chain vs jacfwd vs Cholesky) with
  tensorboard-compatible traces, plus a process-wide scoreboard of
  named wall-clock phases for quick attribution without a trace viewer.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional

__all__ = ["FitStats", "trace", "annotate", "scoreboard", "Scoreboard"]


@dataclass
class FitStats:
    """Structured result of one fit (returned via Fitter.stats)."""

    fitter: str = ""
    ntoa: int = 0
    nfree: int = 0
    dof: int = 0
    chi2: float = float("nan")
    reduced_chi2: float = float("nan")
    iterations: int = 0
    converged: bool = False
    wall_time_s: float = 0.0
    toas_per_sec: float = 0.0
    phases: Dict[str, float] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    def __str__(self) -> str:
        return (f"{self.fitter}: chi2={self.chi2:.3f} "
                f"(red. {self.reduced_chi2:.4f}), "
                f"{self.iterations} iter in {self.wall_time_s * 1e3:.1f} ms "
                f"({self.toas_per_sec:.0f} TOA/s)")


class Scoreboard:
    """Accumulates named wall-clock phases; the cheap always-on half of
    the profiling story (the expensive half is jax.profiler traces)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = [f"{'phase':<28} {'total_s':>10} {'calls':>7} {'avg_ms':>10}"]
        for k in sorted(self.totals, key=self.totals.get, reverse=True):
            t, c = self.totals[k], self.counts[k]
            lines.append(f"{k:<28} {t:>10.3f} {c:>7} {t / c * 1e3:>10.2f}")
        return "\n".join(lines)

    def reset(self):
        self.totals.clear()
        self.counts.clear()


scoreboard = Scoreboard()


@contextlib.contextmanager
def trace(logdir: Optional[str] = None):
    """Capture a jax.profiler device trace around a block (view with
    tensorboard / xprof). No-op when logdir is None."""
    if logdir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region: shows up inside device traces, feeds the
    scoreboard, AND (ISSUE 10) opens a tracer span under the current
    causal context — ONE instrumentation point serves jax.profiler,
    the process scoreboard and the structured trace. With tracing
    off the span is the shared no-op."""
    import jax

    from pint_tpu import obs

    with jax.profiler.TraceAnnotation(name), scoreboard.phase(name), \
            obs.span(name, kind="annotate"):
        yield
