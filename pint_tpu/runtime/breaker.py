"""Per-backend circuit breaker for the dispatch supervisor.

Reference: the classic CLOSED -> OPEN -> HALF_OPEN breaker of a
service mesh, specialized for the failure mode this repo actually
has (CLAUDE.md environment gotchas): the axon TPU tunnel dies for
hours, HANGS rather than errors, and revives in ~tens-of-minute
windows. The reference design (src/pint/fitter.py, DownhillFitter)
never needed one because it never left the host.

States:

- CLOSED: dispatches flow; consecutive infra failures count up and
  trip the breaker at ``threshold``.
- OPEN: dispatches short-circuit to the host fallback without
  touching the backend at all (a wedged tunnel hangs on contact, so
  "try it and see" is exactly the wrong probe). After ``cooldown_s``
  the next dispatch attempt runs the BOUNDED probe.
- HALF_OPEN: the probe answered, one trial dispatch is allowed
  through; success closes the breaker, failure re-opens it with an
  escalated (doubled, capped) cooldown.

The probe is injected by the supervisor (a subprocess backend-init
bounded by a kill timer — the hang-proof recipe of
``bench.accelerator_responsive`` / ``tools/tpu_capture._init_jax``),
so this module stays importable without jax.

Thread safety: all transitions run under one lock; the probe itself
runs outside it (it can take tens of seconds) with a guard so only
one thread probes at a time.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from pint_tpu.runtime import locks

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# cooldown escalation cap: the tunnel stays dead for hours, but a
# probe every <=8 min matches the committed watcher's cadence
# (tools/tpu_watcher.sh SLEEP_S) — no point re-probing faster than
# the thing that would tell us anyway
_MAX_COOLDOWN_S = 480.0


class CircuitBreaker:
    """One backend's health gate. ``allow()`` -> "proceed" | "probe" |
    "reject"; every attempt reports back through ``on_result``."""

    def __init__(self, backend: str, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 probe: Optional[Callable[[], bool]] = None):
        from pint_tpu import config

        self.backend = backend
        self.threshold = (config.breaker_threshold()
                          if threshold is None else int(threshold))
        self.base_cooldown_s = (config.breaker_cooldown_s()
                                if cooldown_s is None
                                else float(cooldown_s))
        self.cooldown_s = self.base_cooldown_s
        self.probe = probe or (lambda: True)
        self.state = CLOSED
        self.failures = 0          # consecutive, CLOSED state
        self.trips = 0             # lifetime OPEN transitions
        self.opened_at: Optional[float] = None
        self._lock = locks.make_lock("breaker.state")
        self._probing = locks.make_lock("breaker.probe")

    # -- gate ----------------------------------------------------------

    def allow(self) -> str:
        """Gate one dispatch attempt. "proceed": breaker closed;
        "probe": half-open trial (caller MUST report on_result);
        "reject": short-circuit to the fallback path."""
        with self._lock:
            if self.state == CLOSED:
                return "proceed"
            if self.state == HALF_OPEN:
                # one trial in flight already — everyone else degrades
                return "reject"
            if time.monotonic() - self.opened_at < self.cooldown_s:
                return "reject"
        # cooldown elapsed: bounded probe, outside the state lock
        # (it can take tens of seconds); only one prober at a time
        if not self._probing.acquire(blocking=False):
            return "reject"
        try:
            ok = bool(self.probe())
        except Exception:
            ok = False
        finally:
            self._probing.release()
        with self._lock:
            if self.state != OPEN:
                # someone else transitioned while we probed
                return "proceed" if self.state == CLOSED else "reject"
            if ok:
                self.state = HALF_OPEN
                return "probe"
            # still dead: re-arm with escalated cooldown
            self.opened_at = time.monotonic()
            self.cooldown_s = min(self.cooldown_s * 2, _MAX_COOLDOWN_S)
            return "reject"

    # -- outcome reporting ---------------------------------------------

    def on_result(self, success: bool):
        with self._lock:
            if success:
                self.state = CLOSED
                self.failures = 0
                self.cooldown_s = self.base_cooldown_s
                self.opened_at = None
                return
            if self.state == HALF_OPEN:
                # trial failed: straight back to OPEN, escalated
                self._trip(escalate=True)
                return
            self.failures += 1
            if self.failures >= self.threshold:
                self._trip(escalate=False)

    def abort_trial(self):
        """The half-open trial ended WITHOUT a backend-health verdict
        (the dispatched callable raised a caller bug before the
        backend mattered): return to OPEN with the cooldown
        unchanged, so the next window re-probes — never leave the
        breaker dangling in HALF_OPEN, which rejects everything."""
        with self._lock:
            if self.state == HALF_OPEN:
                self.state = OPEN
                self.opened_at = time.monotonic()

    def _trip(self, escalate: bool):
        self.state = OPEN
        self.trips += 1
        self.opened_at = time.monotonic()
        if escalate:
            self.cooldown_s = min(self.cooldown_s * 2, _MAX_COOLDOWN_S)
        self.failures = 0

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self.state != CLOSED

    def reset(self):
        with self._lock:
            self.state = CLOSED
            self.failures = 0
            self.cooldown_s = self.base_cooldown_s
            self.opened_at = None

    def snapshot(self) -> dict:
        with self._lock:
            return {"backend": self.backend, "state": self.state,
                    "failures": self.failures, "trips": self.trips,
                    "cooldown_s": round(self.cooldown_s, 3)}
