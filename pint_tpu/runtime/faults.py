"""Deterministic fault injection at the dispatch boundary.

The axon tunnel's real failure modes — silent hangs, transient
connection errors, NaN garbage from a dying device, RTT drifting
124 -> 255 ms mid-session (CLAUDE.md, VERDICT weak #5) — cannot be
reproduced on demand, so every supervisor behavior they trigger
(watchdog timeout, retry, breaker trip, host failover, K re-pick)
would otherwise be untestable on the CPU mesh. This module injects
exactly those faults, deterministically, at the single choke point
every device call now goes through (``DispatchSupervisor.dispatch``).

A plan is a list of rules matched by dispatch-key substring with
per-rule call counters (``after``/``count``), so a test can say "the
2nd and 3rd dispatches of the serve engine hang" and get exactly
that, every run. No randomness anywhere — the same shape of harness
a training/inference stack straps around its collective ops.

Usage::

    plan = FaultPlan([Fault(match="fit_loop", kind="hang",
                            seconds=5.0)])
    with plan.active():
        ...  # every matching dispatch now sleeps past its deadline

While ANY plan is active the supervisor always takes the guarded
worker path (even on the CPU backend, where real hangs cannot
happen) so deadline behavior is exercised by the test suite.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import List, Optional

from pint_tpu.runtime import locks

__all__ = ["Fault", "FaultPlan", "active_plan", "TransientFault",
           "FatalFault"]

KINDS = ("hang", "error", "nan", "rtt_drift",
         # serving-lifecycle kinds (ISSUE 8), consumed by the serve
         # layer rather than the dispatch supervisor: "overload"
         # makes the admission controller treat capacity as
         # exhausted for matching submits (forces the shed-policy
         # path without needing a real million-user burst),
         # "tenant_burst" drains the matching tenant's token bucket
         # (a quota-exceeding tenant on demand), and "kill_restart"
         # kills the engine at the drain boundary mid-burst — a
         # simulated SIGKILL: in-flight futures die with the engine,
         # journal entries stay unacknowledged, and the restart path
         # (AOT restore + journal replay) is what recovers them.
         "overload", "tenant_burst", "kill_restart",
         # fleet kinds (ISSUE 19), consumed by serve.fleet:
         # "worker_kill" kills one named fleet worker mid-burst (its
         # engine dies like kill_restart, its lease stops beating,
         # and the front's expiry sweep re-homes its unacked journal
         # entries onto survivors), "lease_expire" forces one
         # worker's lease to read as expired at the front's next
         # sweep without killing the engine (a live worker whose
         # heartbeats stopped reaching the journal — the split-brain
         # case the ownership transfer must stay safe under).
         "worker_kill", "lease_expire")


class TransientFault(RuntimeError):
    """Injected error the classifier must treat as transient (the
    retry-with-backoff class: connection resets, UNAVAILABLE)."""


class FatalFault(ValueError):
    """Injected error the classifier must treat as fatal (the
    programming-error class: re-raise, no retry, no breaker trip)."""


@dataclass
class Fault:
    """One injection rule.

    match      substring of the dispatch key ("" matches every key)
    kind       "hang" | "error" | "nan" | "rtt_drift" — dispatch
               kinds, consumed by DispatchSupervisor.dispatch — or
               "overload" | "tenant_burst" | "kill_restart" —
               serving-lifecycle kinds, consumed by the serve
               admission controller / scheduler (see KINDS above)
    after      skip this many matching dispatches first
    count      apply to at most this many dispatches (None: forever)
    seconds    hang duration (must exceed the configured deadline to
               simulate a wedge; the guarded worker is abandoned and
               never runs the payload — it sleeps out the duration
               and raises internally, so the daemon thread lingers
               only for ``seconds``, doing no late device work)
    factor     rtt_drift: reported wall = factor x measured wall
    exc        error: exception INSTANCE to raise (default: a
               TransientFault)
    """

    match: str = ""
    kind: str = "hang"
    after: int = 0
    count: Optional[int] = None
    seconds: float = 5.0
    factor: float = 3.0
    exc: Optional[BaseException] = None
    seen: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {KINDS})")

    def applies(self, key: str) -> bool:
        """Match + advance this rule's deterministic counter."""
        if self.match not in key:
            return False
        n = self.seen
        self.seen += 1
        if n < self.after:
            return False
        if self.count is not None and n >= self.after + self.count:
            return False
        return True


class FaultPlan:
    """An activatable set of rules + the injection log.

    ``probe_ok`` overrides the breaker's bounded backend probe while
    the plan is active: False = "tunnel still dead" (half-open never
    opens), True = "tunnel revived" (half-open trial allowed), None =
    use the real probe. Tests flip it mid-plan to script a recovery.
    """

    def __init__(self, rules: Optional[List[Fault]] = None,
                 probe_ok: Optional[bool] = None):
        self.rules: List[Fault] = list(rules or [])
        self.probe_ok = probe_ok
        self.applied: List[tuple] = []   # (key, kind) log for asserts
        self._lock = locks.make_lock("faults.plan")

    def faults_for(self, key: str,
                   kinds: Optional[tuple] = None) -> List[Fault]:
        """The rules firing on this dispatch (counters advanced).

        ``kinds`` scopes the lookup: only rules of those kinds are
        tested (and have their deterministic counters advanced).
        The dispatch supervisor and the serve admission/drain layers
        consume DIFFERENT kinds at DIFFERENT choke points — without
        the scope, an admission check would advance a hang rule's
        ``after`` counter and silently shift which dispatch it fires
        on."""
        with self._lock:
            rules = self.rules if kinds is None else \
                [f for f in self.rules if f.kind in kinds]
            hits = [f for f in rules if f.applies(key)]
            for f in hits:
                self.applied.append((key, f.kind))
            return hits

    def clear(self):
        """Deactivate every rule in place (scripted 'recovery')."""
        with self._lock:
            self.rules.clear()

    @contextlib.contextmanager
    def active(self):
        """Install this plan process-wide for the with-block."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev


_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE
