"""Dispatch supervisor: watchdog deadlines, retry/breaker routing,
host failover, RTT-drift re-measurement.

Reference problem (CLAUDE.md environment gotchas; no reference-repo
analog — src/pint/fitter.py never leaves the host): the axon TPU
tunnel HANGS ``jax.devices()`` and in-flight dispatches without
erroring, dies for entire rounds, revives in ~40-minute windows, and
drifts RTT 124 -> 255 ms mid-session. Before this module every
device-touching call site (device fitter steps, GLS solves, serve
batch dispatches) was an unbounded hang waiting to happen, even
though a bit-correct host CPU path already exists everywhere. The
supervisor makes degraded-but-correct the guaranteed worst case:

- **watchdog deadline**: each dispatch runs on a guarded daemon
  worker; the caller waits at most a deadline predicted from the
  measured RTT x steps-per-dispatch (plus a compile allowance on the
  first call per dispatch key), then gets ``DispatchTimeout`` instead
  of blocking forever ($PINT_TPU_DISPATCH_DEADLINE_MS overrides).
  The worker thread cannot be killed (the hang is inside the XLA
  client); it is abandoned and its eventual result discarded.
- **classification + retry**: transient infra errors (connection
  resets, XLA UNAVAILABLE/RESOURCE_EXHAUSTED, injected
  ``TransientFault``) retry with jittered exponential backoff;
  anything else is a caller bug and re-raises untouched.
- **circuit breaker** (``runtime.breaker``): repeated timeouts/
  transient failures trip the per-backend breaker OPEN, after which
  dispatches short-circuit straight to their host fallback without
  touching the backend (contacting a wedged tunnel hangs). Half-open
  re-probes reuse the hang-proof subprocess probe recipe of
  ``bench.accelerator_responsive`` / ``tools/tpu_capture._init_jax``.
- **host failover**: a dispatch given a ``fallback`` callable returns
  its result (counted, logged) whenever the device path is timed
  out, broken or breaker-open; without one, the classified exception
  propagates so the call site can fail over at a higher level (the
  device fitter falls back to the whole host fitter).
- **RTT drift** (VERDICT r5 "Next round" #7): a guarded dispatch
  whose observed wall deviates >2x from the RTT-based prediction
  triggers a bounded re-measure and a re-pick of the power-of-two
  steps-per-dispatch K (``config.auto_steps_per_dispatch``) — K
  stays inside the quantized {4,8,16,32} set, so compile keys stay
  stable. Pipelined dispatches (in-flight depth > 1) never produce
  drift verdicts: their wall includes queuing behind the work they
  overlapped, so it is not a clean RTT observation in either
  direction.
- **pipeline mode** (``dispatch_async``): the serve scheduler and
  the device fitter issue the NEXT batch/chunk while the current one
  executes (double-buffering on jax's async dispatch). Each async
  dispatch returns a ``DispatchFuture``; the watchdog deadline
  scales by the in-flight depth at issue time (deadline = predicted
  RTT x steps x depth + compile allowance), so a wedged backend with
  a full pipeline still drains every future to labeled host failover
  — zero hung futures. Fault-plan rules are consumed at ISSUE time
  on the caller thread, keeping injection deterministic in issue
  order even though completion order is concurrent.

On the plain CPU backend (every test process) dispatches run inline
— no worker thread, no deadline — because the hang failure mode does
not exist there; an active ``runtime.faults`` plan forces the
guarded path so all of the above is testable on the CPU mesh.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from pint_tpu.runtime import faults, locks
from pint_tpu.runtime.breaker import CircuitBreaker

__all__ = ["DispatchSupervisor", "DispatchFuture", "RuntimeMetrics",
           "DispatchError", "DispatchTimeout", "BackendUnavailable",
           "get_supervisor", "breaker_for", "reset_runtime",
           "bounded_backend_probe"]

# deadline = margin x (rtt x steps), floored: generous by design — the
# watchdog exists to catch the wedged-tunnel hang (minutes/forever),
# not to police a slow-but-live dispatch into a spurious failover
_DEADLINE_MARGIN = 8.0
_DEADLINE_FLOOR_MS = 1000.0
# RTT guess when the backend is an accelerator and nothing has been
# measured yet: the tunnel's measured ceiling (round 4)
_RTT_FALLBACK_MS = 250.0
# the fault kinds the SUPERVISOR consumes at the dispatch boundary —
# serving-lifecycle kinds (overload/tenant_burst/kill_restart) are
# consumed by the serve layer at its own choke points and must not
# have their deterministic counters advanced by dispatch lookups
_DISPATCH_FAULT_KINDS = ("hang", "error", "nan", "rtt_drift")

# drift window: observed wall within [1/2x, 2x] of prediction is fine
_DRIFT_FACTOR = 2.0
# predictions below this are noise on any backend — no drift verdicts
_DRIFT_FLOOR_MS = 5.0


class DispatchError(RuntimeError):
    """Base class for supervised-dispatch infrastructure failures
    (never raised for caller bugs — those re-raise unclassified)."""


class DispatchTimeout(DispatchError, TimeoutError):
    """The watchdog deadline expired; the worker was abandoned."""


class BackendUnavailable(DispatchError):
    """The backend's circuit breaker is open and the call site
    provided no host fallback."""


class RuntimeMetrics:
    """Supervisor counters — the observability contract: a degraded
    run must be LABELED (bench artifacts and serve snapshots embed
    ``snapshot()``), never silently slow.

    ISSUE 11: the counters are REGISTRY-BACKED — each instance holds
    bound children of the process-global ``obs.metrics`` registry
    (``pint_tpu_dispatch_<name>_total``, labelled by a per-instance
    ``scope`` so a serve engine's supervisor stays distinguishable
    from the fitters' global one), and ``snapshot()``/attribute
    reads are derived views of the same values. The dispatch-wall
    HistogramSet shares its rows with the registry's
    ``pint_tpu_dispatch_wall_seconds`` histogram, so /metrics and
    the artifact `latency` block can never disagree."""

    _COUNTERS = ("dispatches", "guarded", "retries", "timeouts",
                 "transient_errors", "failovers",
                 "breaker_rejections", "breaker_recoveries",
                 "abandoned_workers", "rtt_remeasures",
                 "async_dispatches")

    def __init__(self):
        from pint_tpu.obs import HistogramSet
        from pint_tpu.obs import metrics as om

        self._lock = locks.make_lock("runtime.metrics")
        self.scope = om.new_scope("sup")
        self._c = {
            name: om.counter(
                f"pint_tpu_dispatch_{name}_total",
                f"supervisor {name.replace('_', ' ')}"
            ).child(scope=self.scope)
            for name in self._COUNTERS}
        self._g_inflight = om.gauge(
            "pint_tpu_dispatch_max_inflight",
            "peak pipelined in-flight depth").child(scope=self.scope)
        self._g_rtt = om.gauge(
            "pint_tpu_dispatch_last_rtt_ms",
            "last re-measured dispatch RTT").child(scope=self.scope)
        self._g_k = om.gauge(
            "pint_tpu_dispatch_last_k",
            "last re-picked steps-per-dispatch K"
        ).child(scope=self.scope)
        self.last_rtt_ms: Optional[float] = None
        self.last_k: Optional[int] = None
        self.max_inflight = 0   # peak pipelined depth observed
        # per-(pool, key) dispatch-wall histograms (ISSUE 10):
        # log-bucketed, O(1) memory, embedded as the `latency` block
        # of snapshot() — rows shared with the registry histogram
        # (ISSUE 11), how bench artifacts judge tails without
        # per-sample storage
        hist = om.histogram("pint_tpu_dispatch_wall_seconds",
                            "supervised dispatch wall per "
                            "(pool, key)")
        scope = self.scope
        self.latency = HistogramSet(
            row_factory=lambda key, metric: hist.row(
                scope=scope, pool=str(key[0]), key=str(key[1]),
                metric=metric))
        # dispatch-wall decomposition rows (ISSUE 15): per (pool,
        # key) x (queue_wait | host_assembly | device_wall |
        # collect), recorded only when the perf plane is armed
        # ($PINT_TPU_PERF) and the dispatch ran on the guarded
        # worker (the phase boundaries ARE the worker's fn-return /
        # host-read split). Rows shared with the registry histogram,
        # same parity-by-construction as `latency`.
        phist = om.histogram("pint_tpu_perf_dispatch_phase_seconds",
                             "supervised dispatch wall "
                             "decomposition per (pool, key) x phase")
        self.perf = HistogramSet(
            row_factory=lambda key, metric: phist.row(
                scope=scope, pool=str(key[0]), key=str(key[1]),
                metric=metric))

    def __getattr__(self, name):
        # registry-backed counter reads (tests and call sites keep
        # the `metrics.timeouts` attribute surface)
        c = self.__dict__.get("_c")
        if c is not None and not name.startswith("_") and \
                name in type(self)._COUNTERS:
            return int(c[name].value())
        raise AttributeError(name)

    def bump(self, name: str, n: int = 1):
        self._c[name].inc(n)

    def note_inflight(self, depth: int):
        with self._lock:
            self.max_inflight = max(self.max_inflight, depth)
            self._g_inflight.set(self.max_inflight)

    def note_rtt(self, rtt_ms: float, k: int):
        """Record a drift re-measure outcome (value gauges ride the
        registry; the attributes stay the artifact surface)."""
        self.last_rtt_ms = rtt_ms
        self.last_k = k
        self._g_rtt.set(rtt_ms)
        self._g_k.set(k)

    def snapshot(self) -> dict:
        out = {name: int(self._c[name].value())
               for name in self._COUNTERS}
        with self._lock:
            out["max_inflight"] = self.max_inflight
        if self.last_rtt_ms is not None:
            out["last_rtt_ms"] = round(self.last_rtt_ms, 3)
        if self.last_k is not None:
            out["last_k"] = self.last_k
        out["breakers"] = {b: br.snapshot()
                           for b, br in _BREAKERS.items()}
        lat = self.latency.snapshot()
        if lat:
            out["latency"] = lat
        pf = self.perf.snapshot()
        if pf:
            out["perf"] = pf
        return out


# ------------------------------------------------------------------
# per-backend breaker registry (breakers are process-global: backend
# health is a process fact, while supervisor COUNTERS can be
# per-engine so serve accounting stays self-contained)
# ------------------------------------------------------------------

_BREAKERS: dict = {}
_BREAKERS_LOCK = locks.make_lock("runtime.breaker_table")


def bounded_backend_probe(timeout_s: Optional[float] = None) -> bool:
    """Hang-proof backend liveness probe: run the backend init in a
    SUBPROCESS under a kill timer (the bench.accelerator_responsive /
    tpu_capture._init_jax recipe — a wedged tunnel hangs in-process
    ``jax.devices()`` with no error, so probing in-process is the
    bug, not the fix)."""
    from pint_tpu import config

    if timeout_s is None:
        timeout_s = config.breaker_probe_timeout_s()
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s, capture_output=True,
            env=dict(os.environ))  # graftlint: allow G17 -- whole-env passthrough to the hang-probe subprocess (forwards, never parses; the probe needs the caller's PALLAS_AXON_* tunnel vars)
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _probe_for(backend: str) -> Callable[[], bool]:
    def probe() -> bool:
        plan = faults.active_plan()
        if plan is not None and plan.probe_ok is not None:
            return bool(plan.probe_ok)
        if backend == "cpu":
            return True  # the local host cannot wedge like the tunnel
        return bounded_backend_probe()

    return probe


def breaker_for(backend: str) -> CircuitBreaker:
    with _BREAKERS_LOCK:
        if backend not in _BREAKERS:
            _BREAKERS[backend] = CircuitBreaker(
                backend, probe=_probe_for(backend))
        return _BREAKERS[backend]


# ------------------------------------------------------------------
# the supervisor
# ------------------------------------------------------------------


class DispatchSupervisor:
    """Routes device dispatches through deadline/retry/breaker/
    failover policy. One process-global instance serves the fitters
    (``get_supervisor``); ``ServeEngine`` owns its own (self-contained
    counters, shared process-global breakers)."""

    def __init__(self, metrics: Optional[RuntimeMetrics] = None):
        self.metrics = metrics or RuntimeMetrics()
        self._seen: set = set()   # dispatch keys past first call
        self._inflight = 0        # async dispatches currently issued
        self._inflight_lock = locks.make_lock("runtime.inflight")

    # -- public API ----------------------------------------------------

    def dispatch(self, fn, *args, key: str = "dispatch",
                 steps: int = 1, kw: Optional[dict] = None,
                 fallback: Optional[Callable] = None,
                 guard: Optional[bool] = None, pinned: bool = False,
                 depth: int = 1, _plan_hits=None,
                 shadow: Optional[Callable] = None,
                 shadow_kind: Optional[str] = None,
                 info: Optional[dict] = None):
        """Run ``fn(*args, **kw)`` under supervision.

        key       stable label for this call site (deadline first-call
                  compile allowance + fault matching + logs)
        steps     iterations chained inside this one device program
                  (scales the deadline prediction)
        fallback  zero-arg host-path callable; invoked (and counted as
                  a failover) on timeout / transient exhaustion /
                  breaker-open. Without one the DispatchError raises.
        guard     force (True) or suppress (False) the watchdog
                  worker. Default: guarded on accelerator backends and
                  whenever a fault plan is active; inline on plain CPU.
        pinned    the call site pinned this solve to the host CPU
                  device (config.solve_scope) — treated as hang-free,
                  so it stays inline (a worker thread would escape the
                  thread-local device scope).
        depth     in-flight pipeline depth at issue time (set by
                  dispatch_async): scales the watchdog deadline —
                  a pipelined dispatch may legitimately queue behind
                  depth-1 others — and suppresses drift verdicts,
                  whose RTT model only holds for unoverlapped walls.
        shadow    shadow-oracle replay hook (ISSUE 14): a callable
                  ``shadow(out) -> drift_sigma | None`` that re-runs
                  the completed solve on the numpy mirror and
                  returns device-vs-host drift in sigma. The
                  supervisor is the SCHEDULER only: when
                  $PINT_TPU_SHADOW_RATE says this key's Nth
                  successful dispatch is due, the hook runs on a
                  background daemon thread and the drift lands in
                  the ``obs.health`` registry histogram — never on
                  the dispatch's own critical path, never on
                  failover results (a host-mirror result shadowing
                  itself would read as zero drift).
        shadow_kind  health-kind label for the shadow recording
                  (defaults to the dispatch key).
        info      optional caller-owned dict the supervisor marks
                  with ``{"failover": True}`` when this dispatch
                  resolved through its host fallback — so a call
                  site can attribute downstream health verdicts to
                  the pool that ACTUALLY produced the result
                  (the sampling chain tap's /healthz pools).
        _plan_hits  internal: fault-plan rules pre-fetched at ISSUE
                  time by dispatch_async (keeps injection
                  deterministic in issue order); first attempt only,
                  retries re-fetch.

        Every dispatch runs under a tracer span ("dispatch/<key>",
        ISSUE 10) parented by the caller's context — retries,
        timeouts, breaker transitions, failovers and RTT re-measures
        are child events, so a DEGRADED artifact's counters have a
        causal story behind them. With tracing off the span is the
        shared no-op (one branch).
        """
        import jax

        from pint_tpu import obs

        kw = kw or {}
        backend = jax.default_backend()
        # lock sanitizer (ISSUE 18): a guarded dispatch issued while
        # this thread holds a traced ENGINE lock is the blocking-
        # under-lock bug G16 bans statically — one labeled
        # ``lockheld:<name>`` incident per episode, detection only
        # (the dispatch itself proceeds)
        locks.check_dispatch_clear(f"dispatch/{key}")
        with obs.span(f"dispatch/{key}", kind="dispatch",
                      backend=backend, steps=steps, depth=depth,
                      pinned=pinned) as sp:
            # failover marker: a host-fallback result must not be
            # shadowed against the same mirror (vacuous zero drift),
            # and a caller-passed ``info`` dict receives the same
            # mark for its own pool attribution
            fo: dict = info if info is not None else {}
            out = self._dispatch_in_span(
                sp, fn, args, kw, key, steps, fallback, guard,
                pinned, depth, _plan_hits, backend, _fo=fo)
            # never shadow a failover result OR a pinned host solve:
            # both ran on the host CPU, so replaying the numpy
            # mirror against them is a vacuous ~floor comparison
            # that would fill the drift histogram with noise and
            # burn the per-key 1-in-N sampling slots the DEVICE
            # dispatches are supposed to get
            if shadow is not None and not fo.get("failover") \
                    and not pinned:
                self._maybe_shadow(key, shadow_kind or key, shadow,
                                   out)
            return out

    def _maybe_shadow(self, key, kind, shadow, out):
        """Shadow-oracle scheduler (ISSUE 14): rate-gate per key,
        then hand the replay to the health monitor's background
        thread. Never raises into the dispatch path."""
        try:
            from pint_tpu.obs import health as _health

            mon = _health.get_monitor()
            if not mon.shadow_rate or not mon.shadow_due(key):
                return
            from pint_tpu import obs

            obs.event("health.shadow_issue", key=key, kind=kind)
            mon.shadow_replay(kind, key, lambda: shadow(out))
        except Exception:  # the black box must not break dispatch
            pass

    def _dispatch_in_span(self, sp, fn, args, kw, key, steps,
                          fallback, guard, pinned, depth, _plan_hits,
                          backend, _fo: Optional[dict] = None):
        plan = faults.active_plan()
        if guard is None:
            # pinned solves stay inline even under a fault plan: the
            # worker thread would escape the caller's thread-local
            # jax.default_device(cpu) pin and silently execute on the
            # accelerator's non-IEEE f64 (hang faults therefore don't
            # bite pinned dispatches — the pin means host CPU, which
            # cannot wedge; error/nan faults still apply inline)
            guard = (backend != "cpu" or plan is not None) \
                and not pinned
        m = self.metrics
        m.bump("dispatches")
        # pinned dispatches execute on the host CPU device: they
        # carry no evidence about the ACCELERATOR backend's health,
        # so they neither consult nor feed its breaker — a tiny
        # host-pinned solve succeeding must not close a tripped TPU
        # breaker, and an open breaker must not reroute hang-free
        # host solves to the numpy mirrors
        br = None if pinned else breaker_for(backend)
        gate = "proceed" if br is None else br.allow()
        if gate == "reject":
            m.bump("breaker_rejections")
            sp.event("breaker.reject", backend=backend)
            return self._failover(fallback, key, BackendUnavailable(
                f"{backend} backend circuit breaker is open "
                f"(dispatch {key!r} short-circuited to host)"), sp,
                fo=_fo)
        probing = gate == "probe"

        from pint_tpu import config

        retries = config.dispatch_retries()
        deadline_s = self._deadline_s(key, steps, backend,
                                      depth=depth)
        # perf decomposition arming (ISSUE 15): one cached-bool read
        # when disarmed; phases only exist on the guarded worker,
        # whose fn-return/host-read boundaries ARE the split
        perf_on = False
        if guard:
            from pint_tpu.obs import perf as _perf

            perf_on = _perf.enabled()
        attempt = 0
        while True:
            if _plan_hits is not None:
                hits, _plan_hits = _plan_hits, None
            else:
                hits = plan.faults_for(
                    key, kinds=_DISPATCH_FAULT_KINDS) \
                    if plan is not None else []
            pre_sleep = sum(f.seconds for f in hits
                            if f.kind == "hang")
            nan = any(f.kind == "nan" for f in hits)
            inj_err = next((f for f in hits if f.kind == "error"),
                           None)
            drift = 1.0
            for f in hits:
                if f.kind == "rtt_drift":
                    drift *= f.factor
            t0 = time.perf_counter()
            try:
                if inj_err is not None:
                    raise (inj_err.exc if inj_err.exc is not None
                           else faults.TransientFault(
                               f"injected transient error at {key}"))
                ph: Optional[list] = [] if perf_on else None
                if guard:
                    m.bump("guarded")
                    # ph passed only when armed: keeps the call
                    # signature-compatible with test doubles that
                    # wrap _guarded_call positionally
                    if ph is not None:
                        out = self._guarded_call(
                            fn, args, kw, deadline_s, pre_sleep,
                            nan, ph=ph)
                    else:
                        out = self._guarded_call(
                            fn, args, kw, deadline_s, pre_sleep, nan)
                else:
                    out = fn(*args, **kw)
                    if nan:
                        out = _nan_like(out)
            except DispatchTimeout as e:
                # a hang is not worth retrying in-process: another
                # attempt costs another full deadline against a
                # backend that just proved unresponsive
                m.bump("timeouts")
                sp.event("dispatch.timeout",
                         deadline_s=round(deadline_s, 3))
                self._breaker_failure(br, sp, backend)
                return self._failover(fallback, key, e, sp, fo=_fo)
            except BaseException as e:
                if not _is_transient(e):
                    # caller bug: no retry, no breaker verdict — but a
                    # HALF_OPEN trial must not be left dangling (the
                    # breaker would reject everything forever)
                    if probing:
                        br.abort_trial()
                    raise
                m.bump("transient_errors")
                sp.event("dispatch.transient_error", attempt=attempt,
                         error=f"{type(e).__name__}: {e}")
                self._breaker_failure(br, sp, backend)
                if (br is None or not br.is_open) and \
                        attempt < retries:
                    m.bump("retries")
                    sp.event("dispatch.retry", attempt=attempt + 1)
                    time.sleep(_backoff_s(attempt))
                    attempt += 1
                    continue
                return self._failover(fallback, key, e, sp, fo=_fo)
            wall = time.perf_counter() - t0
            if br is not None:
                br.on_result(True)
            if probing:
                m.bump("breaker_recoveries")
                sp.event("breaker.closed", backend=backend)
                _log().warning(
                    "%s backend recovered; circuit breaker closed",
                    backend)
            first_call = key not in self._seen
            self._seen.add(key)
            if first_call:
                # per-compile-key compile wall (ISSUE 11): the first
                # call per key is the one the deadline logic budgets
                # the compile allowance for — its wall IS the
                # trace+compile+dispatch cost of that executable
                from pint_tpu.obs import metrics as om

                om.gauge(
                    "pint_tpu_compile_wall_seconds",
                    "first-call (trace+compile+dispatch) wall per "
                    "dispatch key").set(
                    wall, scope=self.metrics.scope, key=key)
                # ISSUE 15: the same detection feeds the compile
                # LEDGER — every supervised dispatch key (device
                # fits, GLS solves, serve classes, streaming/
                # sampling chunks) gets an entry with its first-call
                # wall; call sites that hold the jit object enrich
                # it with XLA cost analysis (ExecutableCache, bench)
                from pint_tpu.obs import perf as _perf

                _perf.note_compile(key, backend=backend,
                                   compile_wall_s=wall)
            if ph is not None and len(ph) == 3:
                # dispatch-wall decomposition (ISSUE 15): the four
                # phases telescope over [t0, t0+wall] — queue_wait
                # (worker spawn/schedule), host_assembly (fn body up
                # to enqueue), device_wall (the donation-safe
                # _host_read block), collect (worker wake + unbox).
                # Pipelined dispatches keep their own depth in the
                # span; like the PR-7 precedent none of this ever
                # feeds RTT drift.
                t_end = t0 + wall
                qs = max(0.0, ph[0] - t0)
                ha = max(0.0, ph[1] - ph[0])
                dw = max(0.0, ph[2] - ph[1])
                co = max(0.0, t_end - ph[2])
                pkey = ("host" if pinned else backend, key)
                pf = self.metrics.perf
                pf.record(pkey, "queue_wait", qs)
                pf.record(pkey, "host_assembly", ha)
                pf.record(pkey, "device_wall", dw)
                pf.record(pkey, "collect", co)
                sp.event("perf.phases",
                         queue_wait_ms=round(qs * 1e3, 3),
                         host_assembly_ms=round(ha * 1e3, 3),
                         device_wall_ms=round(dw * 1e3, 3),
                         collect_ms=round(co * 1e3, 3),
                         depth=depth)
            # no drift verdict on the first call per key: its wall
            # includes the compile the deadline logic itself budgets
            # a separate allowance for — it would read as "drift" on
            # every cold executable. Pinned (host-CPU) walls carry no
            # information about the ACCELERATOR backend's RTT either
            # (the serve capacity router deliberately runs host-pool
            # dispatches pinned): feeding them to the drift model
            # would read every fast host solve as an under-run.
            if not first_call and not pinned:
                self._note_wall(key, steps, wall * drift, backend,
                                depth=depth)
            self.metrics.latency.record(
                ("host" if pinned else backend, key),
                "dispatch_wall", wall)
            return out

    @staticmethod
    def _breaker_failure(br, sp, backend):
        """Report a failure to the breaker and, when that TRIPS it
        (CLOSED/HALF_OPEN -> OPEN), emit the breaker.open span event
        and trigger a flight-recorder dump — the moment the pool
        router starts demoting is exactly the moment a post-mortem
        wants the black box written."""
        if br is None:
            return
        was_open = br.is_open
        br.on_result(False)
        if br.is_open and not was_open:
            from pint_tpu import obs

            sp.event("breaker.open", backend=backend,
                     trips=br.trips)
            fpath = obs.flight_dump("breaker_open", backend=backend,
                                    breaker=br.snapshot())
            # ISSUE 15: automatic one-shot profiler window capturing
            # the dispatches that follow the trip — armed by
            # $PINT_TPU_PROFILE_DIR, one per episode (per-reason
            # rate limit), never raises into the incident path
            from pint_tpu.obs import perf as _perf

            _perf.auto_window("breaker_open", backend=backend,
                              flight=fpath)

    def dispatch_async(self, fn, *args, key: str = "dispatch",
                       steps: int = 1, kw: Optional[dict] = None,
                       fallback: Optional[Callable] = None,
                       guard: Optional[bool] = None,
                       pinned: bool = False) -> "DispatchFuture":
        """Issue a supervised dispatch WITHOUT waiting for it — the
        pipeline mode. Returns a ``DispatchFuture`` whose ``result()``
        delivers exactly what the synchronous ``dispatch`` would have
        returned (same retry / breaker / failover policy, including
        the host-fallback result on timeout), so a caller that issues
        N futures and collects them all is guaranteed N completions —
        never a hung future.

        The watchdog deadline of each async dispatch scales with the
        in-flight depth at its issue time (a dispatch queued behind
        depth-1 others may legitimately wait depth x RTT x steps
        before its own work even starts), and pipelined dispatches
        are excluded from RTT-drift verdicts (config.
        auto_steps_per_dispatch: overlapped walls are not clean RTT
        observations). Fault-plan rules are consumed HERE, on the
        caller thread, so deterministic injection follows issue
        order."""
        from pint_tpu import obs

        plan = faults.active_plan()
        plan_hits = plan.faults_for(key, kinds=_DISPATCH_FAULT_KINDS) \
            if plan is not None else []
        with self._inflight_lock:
            self._inflight += 1
            depth = self._inflight
        self.metrics.bump("async_dispatches")
        self.metrics.note_inflight(depth)
        fut = DispatchFuture(key)
        # span context captured at ISSUE time on the caller thread:
        # the worker re-enters it so the dispatch span (and its
        # retry/timeout/failover children) parent under the serve
        # unit / fit that issued this pipeline slot — under
        # pipelining, issue and collect are separate spans of the
        # same causal story (ISSUE 10)
        ctx = obs.current()
        obs.event("dispatch.issue", key=key, depth=depth)

        def work():
            try:
                with obs.attach(ctx):
                    fut._set_result(self.dispatch(
                        fn, *args, key=key, steps=steps, kw=kw,
                        fallback=fallback, guard=guard, pinned=pinned,
                        depth=depth, _plan_hits=plan_hits))
            except BaseException as e:
                fut._set_exception(e)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

        t = threading.Thread(target=work, daemon=True,
                             name=f"pint-dispatch-async-{key}")
        t.start()
        return fut

    # -- pipeline introspection ---------------------------------------

    @property
    def inflight(self) -> int:
        """Async dispatches issued and not yet completed."""
        with self._inflight_lock:
            return self._inflight

    def pool_health(self, pools=None) -> dict:
        """Capacity-pool health surface for the serve router (ISSUE
        8): the device pool's breaker state + in-flight depth, and
        the host pool (always available — the local host cannot
        wedge like the tunnel; its 'breaker' is definitionally
        closed). Read-only: consulting this never probes the
        backend, so it is safe to call per routing decision.

        ``pools`` (ISSUE 19) names EXTRA device-class pools beyond
        the classic pair: each gets its own process-global
        ``runtime.breaker`` instance keyed ``pool:<name>`` (an open
        breaker demotes only that pool), reported alongside device/
        host in the same shape — the surface the N-pool router and
        the /healthz ``pools`` block read."""
        import jax

        backend = jax.default_backend()
        out = {
            "device": {
                "backend": backend,
                "breaker": breaker_for(backend).snapshot(),
                "open": breaker_for(backend).is_open,
                "inflight": self.inflight,
            },
            "host": {"backend": "cpu", "open": False},
        }
        for name in pools or ():
            if name in out:
                continue
            br = breaker_for(f"pool:{name}")
            out[name] = {"backend": f"pool:{name}",
                         "breaker": br.snapshot(),
                         "open": br.is_open,
                         "inflight": 0}
        return out

    def note_failover(self, key: str, exc: BaseException, sp=None):
        """Record a failover — performed by the CALL SITE (the
        device fitter swaps in the whole host fitter rather than a
        single fallback solve) or by ``_failover`` below, which
        passes its dispatch span so the event lands under it; call
        sites emit at the ambient context."""
        from pint_tpu import obs

        self.metrics.bump("failovers")
        err = f"{type(exc).__name__}: {exc}"
        if sp is not None:
            sp.event("dispatch.failover", key=key, error=err)
        else:
            obs.event("dispatch.failover", key=key, error=err)
        _log().warning("dispatch %s degraded to the host path: %s",
                       key, exc)

    def snapshot(self) -> dict:
        return self.metrics.snapshot()

    # -- internals -----------------------------------------------------

    def _failover(self, fallback, key, exc, sp=None, fo=None):
        if fo is not None:
            fo["failover"] = True
        if fallback is None:
            raise exc
        self.note_failover(key, exc, sp=sp)
        return fallback()

    def _guarded_call(self, fn, args, kw, deadline_s, pre_sleep,
                      nan, ph: Optional[list] = None):
        """``ph`` (ISSUE 15): a caller-owned list the worker fills
        with its three phase boundaries — worker start, fn return
        (host assembly + enqueue done) and host-read return (device
        work + D2H done) — when the perf decomposition is armed.
        The fn-return / host-read split is exactly the
        donation-safe ``_host_read`` boundary: on an async backend
        ``fn`` returns at enqueue, so the read wall IS the device
        wall + collect copy."""
        box: dict = {}
        done = threading.Event()

        def work():
            try:
                if ph is not None:
                    ph.append(time.perf_counter())
                if pre_sleep:
                    # injected wedge: a real wedge never completes, so
                    # the payload is never run — the worker sleeps out
                    # the injected duration and raises into the
                    # (abandoned) box instead of doing late device
                    # work at interpreter-teardown time. A hang
                    # SHORTER than the deadline therefore degrades to
                    # a transient error, not a slow success.
                    time.sleep(pre_sleep)
                    raise faults.TransientFault(
                        "injected hang elapsed (dispatch abandoned)")
                out = fn(*args, **kw)
                if ph is not None:
                    ph.append(time.perf_counter())
                # force the host read INSIDE the worker: an async jax
                # dispatch returns after ENQUEUE (the axon tunnel
                # happily acks enqueue and then wedges), so without
                # this the caller's first np.asarray/float would
                # block unbounded OUTSIDE the watchdog — the exact
                # hang this supervisor exists to eliminate
                out = _host_read(out)
                if ph is not None:
                    ph.append(time.perf_counter())
                if nan:
                    out = _nan_like(out)
                box["out"] = out
            except BaseException as e:  # delivered to the caller
                box["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=work, daemon=True,
                             name="pint-dispatch-worker")
        t.start()
        if not done.wait(deadline_s):
            self.metrics.bump("abandoned_workers")
            raise DispatchTimeout(
                f"dispatch exceeded its {deadline_s:.1f}s watchdog "
                f"deadline (wedged tunnel?); worker abandoned")
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    def _deadline_s(self, key, steps, backend,
                    depth: int = 1) -> float:
        """Watchdog deadline: margin x RTT x steps, scaled by the
        in-flight pipeline depth at issue (a pipelined dispatch may
        queue behind depth-1 predecessors before its own work
        starts), plus the first-call compile allowance."""
        from pint_tpu import config

        env = config.dispatch_deadline_ms()
        if env is not None:
            # the hard override is PER DISPATCH; a pipelined dispatch
            # still waits out its predecessors, so the in-flight
            # window multiplies it too
            return float(env) * max(1, depth) / 1e3
        rtt = self._peek_rtt_ms(backend)
        if rtt is None:
            rtt = self._measure_rtt_guarded()
        dl = max(_DEADLINE_FLOOR_MS,
                 _DEADLINE_MARGIN * rtt * max(1, steps)
                 * max(1, depth))
        if key not in self._seen:
            dl += config.dispatch_compile_allowance_ms()
        return dl / 1e3

    @staticmethod
    def _peek_rtt_ms(backend) -> Optional[float]:
        """The RTT the deadline/drift logic may use WITHOUT triggering
        a measurement (the VALIDATED env override or the per-backend
        cache); None when nothing is known yet."""
        from pint_tpu import config

        env = config.dispatch_rtt_override_ms()
        if env is not None:
            return env
        if backend == "cpu" or backend in config._RTT_MS:
            return config.dispatch_rtt_ms()
        return None

    def _measure_rtt_guarded(self) -> float:
        """First RTT measurement on an accelerator backend: the probe
        dispatch itself can hang on a wedged tunnel, so run it under
        the watchdog with the bounded-probe timeout; fall back to the
        tunnel's measured ceiling. The fallback is CACHED into the
        per-backend RTT table — without that, every dispatch against
        a dead-from-the-start tunnel would repeat the full probe
        timeout before even starting its own deadline wait (the cache
        is dropped again by any later drift re-measure)."""
        import jax

        from pint_tpu import config

        try:
            return float(self._guarded_call(
                config.dispatch_rtt_ms, (), {},
                config.breaker_probe_timeout_s(), 0.0, False))
        except DispatchError:
            self.metrics.bump("timeouts")
        except Exception:
            pass
        config._RTT_MS[jax.default_backend()] = _RTT_FALLBACK_MS
        return _RTT_FALLBACK_MS

    def _note_wall(self, key, steps, wall_s, backend,
                   depth: int = 1):
        """RTT drift detector (VERDICT r5 #7): observed dispatch wall
        deviating >2x from prediction triggers a re-measure and a
        re-pick of the power-of-two K. The window is anchored on the
        FIXED dispatch cost, the only part the RTT model actually
        predicts: a chained wall is rtt + K*t_step with t_step
        unknown, so under-run fires against rtt ALONE (wall < rtt/2
        is impossible when the cached RTT is honest — the fixed cost
        is a lower bound) and over-run against the fully-serial bound
        rtt*K (wall > 2*rtt*K is slower than even zero amortization).
        A healthy chained dispatch (wall ~ rtt + K*t_step, t_step <<
        rtt — the only regime K>1 is chosen for) sits inside the
        window and never false-fires. Compile keys stay stable: K
        remains inside {4,8,16,32}
        (config.auto_steps_per_dispatch quantization).

        PIPELINED dispatches (in-flight depth > 1) get NO verdict in
        either direction: once overlapped, a dispatch's wall is no
        longer RTT-dominated — it includes queuing behind up to
        depth-1 predecessors (a spurious over-run) while the pipeline
        amortizes the fixed cost the under-run bound assumes is
        serial. Either false verdict would re-pick K off a corrupted
        sample; only unoverlapped walls feed the RTT model."""
        from pint_tpu import config

        if depth > 1:
            return
        if config.dispatch_rtt_override_ms() is not None:
            # operator-pinned RTT: a re-measure would only re-read the
            # env — drifting away from a pin is not possible, so a
            # verdict is pure warning churn (e.g. a CPU-fallback run
            # with the tunnel-tuned value still exported)
            return
        rtt = self._peek_rtt_ms(backend)
        if rtt is None or rtt < _DRIFT_FLOOR_MS:
            return
        wall_ms = wall_s * 1e3
        under = wall_ms < rtt / _DRIFT_FACTOR
        over = wall_ms > _DRIFT_FACTOR * rtt * max(1, steps)
        if not (under or over):
            return
        predicted_ms = rtt * max(1, steps)
        self.metrics.bump("rtt_remeasures")
        try:
            new_rtt = float(self._guarded_call(
                config.remeasure_dispatch_rtt, (), {},
                config.breaker_probe_timeout_s(), 0.0, False))
        except Exception:
            return
        self.metrics.note_rtt(new_rtt,
                              config.auto_steps_per_dispatch())
        from pint_tpu import obs

        obs.event("rtt.remeasure", key=key,
                  wall_ms=round(wall_ms, 2),
                  predicted_ms=round(predicted_ms, 2),
                  new_rtt_ms=round(new_rtt, 2),
                  new_k=self.metrics.last_k)
        _log().warning(
            "dispatch %s wall %.1f ms vs predicted %.1f ms (>%.0fx "
            "drift): re-measured RTT %.1f ms, steps-per-dispatch "
            "re-picked to %d", key, wall_ms, predicted_ms,
            _DRIFT_FACTOR, new_rtt, self.metrics.last_k)


class DispatchFuture:
    """Handle for one pipelined supervised dispatch
    (``DispatchSupervisor.dispatch_async``).

    ``result()`` blocks until the dispatch completes and returns what
    the synchronous ``dispatch`` would have — including the host
    FALLBACK's result when the device path timed out / broke /
    short-circuited, so collecting every issued future is a drain
    guarantee, not a best effort. The underlying dispatch runs under
    its own depth-scaled watchdog deadline; ``result`` therefore
    terminates without needing a timeout of its own (an optional one
    is accepted as a belt-and-suspenders bound for callers that want
    it)."""

    def __init__(self, key: str):
        self.key = key
        self._done = threading.Event()
        self._out = None
        self._exc: Optional[BaseException] = None

    def _set_result(self, out):
        self._out = out
        self._done.set()

    def _set_exception(self, exc: BaseException):
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise DispatchTimeout(
                f"async dispatch {self.key!r} did not complete "
                f"within the caller's {timeout}s result() bound")
        if self._exc is not None:
            raise self._exc
        return self._out


# ------------------------------------------------------------------
# helpers
# ------------------------------------------------------------------

# substrings marking an exception as INFRA (retry + breaker) rather
# than a caller bug; XlaRuntimeError carries gRPC-style status text
_TRANSIENT_MARKERS = ("unavailable", "resource_exhausted",
                      "deadline_exceeded", "connection", "socket",
                      "aborted", "tunnel", "failed to connect")


def _is_transient(exc: BaseException) -> bool:
    if isinstance(exc, faults.TransientFault):
        return True
    # deliberately NOT bare OSError: FileNotFoundError/PermissionError
    # etc. are caller bugs that must re-raise, not retry/trip breakers
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    msg = str(exc).lower()
    if type(exc).__name__ == "XlaRuntimeError":
        return any(mk in msg for mk in _TRANSIENT_MARKERS)
    return False


def _backoff_s(attempt: int) -> float:
    """Jittered exponential backoff (base $PINT_TPU_DISPATCH_BACKOFF_MS)."""
    import random

    from pint_tpu import config

    base = config.dispatch_backoff_ms() / 1e3 * (2 ** attempt)
    return base * (1.0 + 0.5 * random.random())


def _host_read(out):
    """Materialize every jax-array leaf as a host numpy array (a
    completed D2H read — the only sync primitive the tunnel cannot
    lie about; ``block_until_ready`` over axon acks enqueue only).
    With buffer donation enabled (config.donation_enabled) the read
    is an OWNED array, never a borrowed view: donated executables'
    outputs can alias donated input buffers, and a zero-copy
    np.asarray view of that memory escaping the dispatch would
    dangle once XLA's allocator reuses it — the runtime counterpart
    of graftlint G11. The copy is paid only when np.asarray actually
    returned a view (the CPU zero-copy case): an accelerator D2H
    read already materializes a fresh owned host buffer, and large
    non-view outputs — PTA batch covariances — never pay a second
    memcpy. With donation off the view is kept (no aliasing is
    possible). Non-array leaves and plain numpy pass through
    untouched."""
    import jax
    import numpy as np

    from pint_tpu.config import donation_enabled

    ensure_owned = donation_enabled()

    def leaf(x):
        if isinstance(x, jax.Array):
            h = np.asarray(x)
            if ensure_owned and not h.flags.owndata:
                h = h.copy()
            return h
        return x

    return jax.tree_util.tree_map(leaf, out)


def _nan_like(out):
    """Injected-NaN transform: every floating leaf becomes all-NaN
    (what a dying device's garbage readback looks like downstream)."""
    import jax
    import numpy as np

    def leaf(x):
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.floating):
            return np.full_like(a, np.nan)
        return x

    return jax.tree_util.tree_map(leaf, out)


def _log():
    from pint_tpu.logging import log

    return log


# ------------------------------------------------------------------
# process-global supervisor + test reset
# ------------------------------------------------------------------

_GLOBAL: Optional[DispatchSupervisor] = None
_GLOBAL_LOCK = locks.make_lock("runtime.global_supervisor")


def get_supervisor() -> DispatchSupervisor:
    """The process-global supervisor used by the fitters and the PTA
    batch path (serve engines own their own for self-contained
    accounting; breakers are shared either way)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DispatchSupervisor()
        return _GLOBAL


def reset_runtime():
    """Drop all breakers + reset the global supervisor's counters
    (tests: a tripped breaker — or one constructed under a
    monkeypatched threshold — must never leak into the next test)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.metrics = RuntimeMetrics()
            _GLOBAL._seen.clear()
