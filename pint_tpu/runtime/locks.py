"""Traced locks + the process lock-order graph (ISSUE 18).

The serve/dispatch stack is ~40 hand-audited ``threading`` lock
sites whose discipline ("MetricsServer never takes an engine lock",
"no dispatch under the engine lock", journal fsync outside the cv)
was, before this module, asserted by one test each. This module is
the DYNAMIC half of the concurrency plane (graftlint G16 is the
static half): every lock in the dispatch/serve/obs layers is now
constructed through the factories below, so one env knob turns the
whole process into a ThreadSanitizer-style checked build.

- ``make_lock(name)`` / ``make_rlock(name)`` / ``make_condition``:
  disarmed ($PINT_TPU_LOCK_TRACE unset — the production default)
  they return the BARE stdlib primitives, a true zero-cost
  passthrough (banded <1% on the north-star step in bench's ``obs``
  block). Armed, they return ``TracedLock``/``TracedRLock`` wrappers
  that record per-thread acquisition ORDER into a process-global
  lock-order graph keyed by lock NAME (discipline is a property of
  the lock class, not the instance — two engines' ``serve.engine``
  locks are one node).
- **cycle detection**: adding edge A->B while B already reaches A in
  the graph is an inversion that can deadlock; it fires ONE
  ``lockorder:<A->B>`` incident per edge per episode — registry
  counter, ``obs.event``, rate-limited flight dump — the exact
  ``numerics:<reason>`` pattern of obs/health.py.
- **dispatch-under-engine-lock**: locks constructed with
  ``engine=True`` (the serve scheduler's cv/dispatch locks) register
  in the per-thread held set; ``DispatchSupervisor`` asks
  ``check_dispatch_clear()`` before a guarded dispatch, and a held
  engine lock fires ONE ``lockheld:<name>`` incident per lock name
  per episode (blocking-under-lock is the classic tail-latency bug
  G16 part 3 bans statically).
- **hold/contention accounting**: per-name ``pint_tpu_lock_wait_
  seconds`` / ``pint_tpu_lock_hold_seconds`` histograms ride the
  obs.metrics registry.
- ``reset()`` drops the graph, the per-edge incident latches and the
  arming cache (wired into ``obs.reset()`` — the test-isolation
  contract of every other obs plane).

Pure stdlib at import time (the runtime package property — obs
modules construct their locks through here without pulling jax);
config/obs/metrics are imported lazily, and only on ARMED paths.
``TracedRLock`` implements the private ``Condition`` protocol
(``_is_owned``/``_release_save``/``_acquire_restore``) so the serve
scheduler's ``Condition(engine_lock)`` works traced.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["TracedLock", "TracedRLock", "make_lock", "make_rlock",
           "make_plane_lock", "make_condition", "check_dispatch_clear",
           "configure", "reset", "status", "lock_graph_edges",
           "held_locks"]

# the plane's own guard — the one lock that cannot be traced
# without infinite recursion
_STATE_LOCK = threading.Lock()  # graftlint: allow G16 -- the lock-order graph's own guard cannot be a traced lock (tracing it would recurse into the graph it protects)

_ARMED: Optional[bool] = None

# lock-order graph: name -> set of names acquired while holding it
_EDGES: dict = {}
# per-edge / per-lock-name incident latches: exactly one labeled
# incident per episode (reset() ends the episode), with the flight
# recorder's per-reason min_interval as the second rate-limit layer
_FIRED_EDGES: set = set()
_FIRED_HELD: set = set()

_TLS = threading.local()


def _armed() -> bool:
    global _ARMED
    if _ARMED is None:
        from pint_tpu import config

        _ARMED = config.lock_trace_enabled()
    return _ARMED


def configure(enabled: Optional[bool] = None):
    """Explicit arming override (tests, bench's off/on legs); None
    drops back to the $PINT_TPU_LOCK_TRACE env default. Only affects
    locks constructed AFTER the call — the obs.reset() contract
    (consumers built before keep their old primitives)."""
    global _ARMED
    with _STATE_LOCK:
        _ARMED = None if enabled is None else bool(enabled)


def reset():
    """Drop the graph, the incident latches and the arming cache
    (the ``obs.reset()`` isolation contract). Existing TracedLocks
    keep working — they just start painting a fresh graph."""
    global _ARMED
    with _STATE_LOCK:
        _ARMED = None
        _EDGES.clear()
        _FIRED_EDGES.clear()
        _FIRED_HELD.clear()


def _held_list() -> list:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def held_locks() -> list:
    """Names of the traced locks the CURRENT thread holds, in
    acquisition order (diagnostics + the dispatch-clear check)."""
    return [e[0].name for e in _held_list()]


def lock_graph_edges() -> dict:
    """Snapshot of the lock-order graph ({name: sorted successors})."""
    with _STATE_LOCK:
        return {a: sorted(bs) for a, bs in _EDGES.items()}


def status() -> dict:
    with _STATE_LOCK:
        return {"armed": bool(_ARMED),
                "edges": sum(len(b) for b in _EDGES.values()),
                "nodes": len(_EDGES),
                "cycles_fired": len(_FIRED_EDGES),
                "held_fired": len(_FIRED_HELD)}


def _reaches(src: str, dst: str) -> bool:
    """BFS over _EDGES — caller holds _STATE_LOCK."""
    seen = {src}
    todo = [src]
    while todo:
        cur = todo.pop()
        if cur == dst:
            return True
        for nxt in _EDGES.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                todo.append(nxt)
    return False


def _incident(reason: str, **extra):
    """One labeled concurrency incident: registry counter,
    ``obs.event``, rate-limited flight dump, warning log — the
    ``numerics:<reason>`` pattern (obs/health.py's _incident)."""
    _TLS.in_plane = True  # the counter/event/dump path takes plane locks
    try:
        from pint_tpu import obs
        from pint_tpu.obs import metrics as om

        om.counter(
            "pint_tpu_lock_incidents_total",
            "lock-order cycles + dispatch-under-engine-lock "
            "detections (runtime.locks)").inc(
            reason=reason.split(":", 1)[0])
        obs.event("locks.incident", reason=reason, **extra)
        obs.flight_dump(reason, **extra)
    except Exception:
        pass
    try:
        from pint_tpu.logging import log

        log.warning("lock-sanitizer incident %s: %r", reason, extra)
    except Exception:
        pass
    finally:
        _TLS.in_plane = False


def _note_acquire(lock, waited_s: float):
    if getattr(_TLS, "in_plane", False):
        # plane-internal: the registry/histogram/flight locks the
        # recording below acquires must not re-enter the bookkeeping
        # (a non-reentrant row lock would deadlock on its own
        # hold-time record)
        return
    held = _held_list()
    for e in held:
        if e[0] is lock:
            e[1] += 1          # reentrant re-acquire: no new edge
            return
    name = lock.name
    new_cycle = None
    with _STATE_LOCK:
        for e in held:
            a = e[0].name
            if a == name:
                continue       # sibling instance of the same class
            succ = _EDGES.setdefault(a, set())
            if name not in succ:
                # adding a->name closes a cycle iff name already
                # reaches a through the painted graph
                if _reaches(name, a):
                    edge = f"{a}->{name}"
                    if edge not in _FIRED_EDGES:
                        _FIRED_EDGES.add(edge)
                        new_cycle = edge
                succ.add(name)
    if new_cycle is not None:
        _incident(f"lockorder:{new_cycle}", edge=new_cycle,
                  thread=threading.current_thread().name,
                  held=[e[0].name for e in held])
    held.append([lock, 1, time.perf_counter()])
    if waited_s > 0.0:
        _TLS.in_plane = True
        try:
            from pint_tpu.obs import metrics as om

            om.histogram(
                "pint_tpu_lock_wait_seconds",
                "contention wait per traced-lock class").observe(
                waited_s, lock=name)
        except Exception:
            pass
        finally:
            _TLS.in_plane = False


def _note_release(lock, full: bool = False):
    if getattr(_TLS, "in_plane", False):
        return
    held = _held_list()
    for i in range(len(held) - 1, -1, -1):
        e = held[i]
        if e[0] is lock:
            e[1] = 0 if full else e[1] - 1
            if e[1] <= 0:
                del held[i]
                _TLS.in_plane = True
                try:
                    from pint_tpu.obs import metrics as om

                    om.histogram(
                        "pint_tpu_lock_hold_seconds",
                        "hold time per traced-lock class").observe(
                        time.perf_counter() - e[2], lock=lock.name)
                except Exception:
                    pass
                finally:
                    _TLS.in_plane = False
            return


def check_dispatch_clear(what: str = "dispatch") -> bool:
    """Called by the supervisor at the guarded-dispatch boundary: a
    held ENGINE lock on the dispatching thread means a scheduler is
    blocking on device work (the G16 part-3 bug, caught live). Fires
    one ``lockheld:<name>`` incident per lock name per episode;
    returns True when clear. Free when no traced engine lock is held
    — the disarmed build never constructs one."""
    held = _held_list()
    bad = [e[0].name for e in held if getattr(e[0], "engine", False)]
    if not bad:
        return True
    for name in bad:
        with _STATE_LOCK:
            if name in _FIRED_HELD:
                continue
            _FIRED_HELD.add(name)
        _incident(f"lockheld:{name}", what=what, lock=name,
                  thread=threading.current_thread().name,
                  held=[e[0].name for e in held])
    return False


class _TracedBase:
    """Shared acquire/release bookkeeping over an inner stdlib
    primitive. ``name`` keys the order graph; ``engine=True`` marks
    a scheduler/engine lock for the dispatch-clear check."""

    def __init__(self, inner, name: str, engine: bool = False):
        self._inner = inner
        self.name = name
        self.engine = bool(engine)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self, time.perf_counter() - t0)
        return ok

    def release(self):
        # physical release FIRST: the hold-time record below touches
        # obs.metrics row locks, and when THIS lock is such a row's
        # lock (registry.render() iterating the lock histograms) a
        # note-then-release order re-acquires the still-held inner
        # primitive — self-deadlock. The held-list pop is thread-
        # local, so nothing observes the tiny reorder window.
        self._inner.release()
        _note_release(self)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return (f"<{type(self).__name__} {self.name!r} "
                f"engine={self.engine}>")


class TracedLock(_TracedBase):
    def __init__(self, name: str, engine: bool = False):
        super().__init__(threading.Lock(), name, engine)  # graftlint: allow G16 -- the traced wrapper's own inner primitive; every consumer reaches it through make_lock

    def locked(self) -> bool:
        return self._inner.locked()


class TracedRLock(_TracedBase):
    """Reentrant traced lock implementing the private stdlib
    ``Condition`` protocol, so ``threading.Condition(TracedRLock)``
    works: ``wait()`` fully releases through ``_release_save`` (we
    drop the held entry and its hold time) and re-registers through
    ``_acquire_restore``."""

    def __init__(self, name: str, engine: bool = False):
        super().__init__(threading.RLock(), name, engine)  # graftlint: allow G16 -- the traced wrapper's own inner primitive; every consumer reaches it through make_rlock

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        st = self._inner._release_save()  # release-then-note, as release()
        _note_release(self, full=True)
        return st

    def _acquire_restore(self, state):
        t0 = time.perf_counter()
        self._inner._acquire_restore(state)
        _note_acquire(self, time.perf_counter() - t0)


def make_lock(name: str, engine: bool = False):
    """A mutex for the dispatch/serve/obs layers: bare
    ``threading.Lock`` disarmed, ``TracedLock`` armed. New lock
    checklist (CLAUDE.md Conventions): construct through here,
    register guarded fields in ``analysis/lock_registry.py``,
    justify any raw construction with a G16 pragma."""
    if not _armed():
        return threading.Lock()  # graftlint: allow G16 -- the disarmed factory IS the sanctioned passthrough (zero-overhead production default)
    return TracedLock(name, engine=engine)


def make_plane_lock(name: str):
    """A BARE mutex for the obs RECORDING plane's own leaf rows
    (metric/histogram rows, the registry): the sanitizer records
    hold/wait histograms THROUGH those locks on every traced
    acquire/release, so tracing them is self-referential — e.g.
    ``render()`` acquiring the wait-histogram row's lock would
    trigger a wait-record into that same row and physically
    re-acquire the held, non-reentrant primitive (the _STATE_LOCK
    rationale, one layer up). Construction still flows through this
    module so the G16 raw-primitive check sees it declared; ``name``
    is kept for greppability/symmetry with make_lock."""
    del name
    return threading.Lock()  # graftlint: allow G16 -- the recording plane's own leaf locks must stay bare: the sanitizer records through them (self-reference deadlock if traced)


def make_rlock(name: str, engine: bool = False):
    """Reentrant sibling of ``make_lock``."""
    if not _armed():
        return threading.RLock()  # graftlint: allow G16 -- the disarmed factory IS the sanctioned passthrough (zero-overhead production default)
    return TracedRLock(name, engine=engine)


def make_condition(lock):
    """``threading.Condition`` over a factory-made lock (traced or
    bare — TracedRLock implements the Condition protocol)."""
    return threading.Condition(lock)  # graftlint: allow G16 -- the factory itself; Condition wraps the already-traced (or sanctioned-bare) lock
