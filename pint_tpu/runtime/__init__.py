"""Fault-tolerant device dispatch (the runtime supervision layer).

The ROADMAP north star is a production system serving heavy traffic
"as fast as the hardware allows" — over a hardware link that
demonstrably wedges, dies and drifts (CLAUDE.md environment gotchas).
This package makes degraded-but-correct the guaranteed worst case
instead of a lucky one. Every device-touching call site (device
fitter steps in ``gls.py``, host-fitter GLS/WLS solves, the PTA batch
solve, ``serve`` batch dispatches) routes through here:

- ``runtime.supervisor``: the ``DispatchSupervisor`` — watchdog
  deadlines on a guarded worker, transient-error retry with jittered
  backoff, host failover, RTT-drift re-measure + K re-pick, and the
  counters every bench artifact embeds so degraded runs are labeled;
- ``runtime.breaker``: per-backend circuit breaker (CLOSED/OPEN/
  HALF_OPEN) with bounded hang-proof re-probes;
- ``runtime.faults``: deterministic fault injection (hang, transient
  error, NaN output, RTT drift) at the dispatch boundary, so every
  behavior above is testable on the CPU mesh.

Env knobs: $PINT_TPU_DISPATCH_DEADLINE_MS (hard deadline override),
$PINT_TPU_DISPATCH_RETRIES, $PINT_TPU_DISPATCH_BACKOFF_MS,
$PINT_TPU_BREAKER_THRESHOLD, $PINT_TPU_BREAKER_COOLDOWN_S,
$PINT_TPU_BREAKER_PROBE_TIMEOUT_S (see ``pint_tpu.config``).
"""

from pint_tpu.runtime.breaker import (  # noqa: F401
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from pint_tpu.runtime.locks import (  # noqa: F401
    TracedLock,
    TracedRLock,
    make_condition,
    make_lock,
    make_rlock,
)
from pint_tpu.runtime.faults import (  # noqa: F401
    Fault,
    FaultPlan,
    FatalFault,
    TransientFault,
    active_plan,
)
from pint_tpu.runtime.supervisor import (  # noqa: F401
    BackendUnavailable,
    DispatchError,
    DispatchFuture,
    DispatchSupervisor,
    DispatchTimeout,
    RuntimeMetrics,
    bounded_backend_probe,
    breaker_for,
    get_supervisor,
    reset_runtime,
)
