"""Analytic solar-system positions: Keplerian planetary elements (JPL
"Approximate Positions of the Planets", Standish, valid 1800–2050 AD) plus
a truncated lunar theory (Meeus-level leading terms) for the EMB→Earth
offset, and a mass-weighted Sun-wrt-SSB correction.

Accuracy, stated honestly: Earth wrt SSB good to ~1e-4 rad (~1.5e4 km,
~50 ms of Roemer delay) vs the real solar system. Everything downstream
is *internally consistent* — the simulate→fit oracle, derivative checks,
and benchmarks are unaffected; real-data work needs an SPK kernel
(pint_tpu.ephemeris.spk).

All outputs: ICRS-equatorial-ish J2000 frame, meters and m/s, wrt SSB.
(reference: src/pint/solar_system_ephemerides.py objPosVel_wrt_SSB)
"""

from __future__ import annotations

import numpy as np

AU = 1.495978707e11  # m
DAY = 86400.0
MJD_J2000 = 51544.5
EPS0 = 84381.406 * np.pi / (180 * 3600)  # J2000 mean obliquity (rad)

# (a [au], a_dot/cy, e, e_dot, I [deg], I_dot, L [deg], L_dot,
#  varpi [deg], varpi_dot, Omega [deg], Omega_dot)
_ELEMENTS = {
    "mercury": (0.38709927, 0.00000037, 0.20563593, 0.00001906,
                7.00497902, -0.00594749, 252.25032350, 149472.67411175,
                77.45779628, 0.16047689, 48.33076593, -0.12534081),
    "venus": (0.72333566, 0.00000390, 0.00677672, -0.00004107,
              3.39467605, -0.00078890, 181.97909950, 58517.81538729,
              131.60246718, 0.00268329, 76.67984255, -0.27769418),
    "emb": (1.00000261, 0.00000562, 0.01671123, -0.00004392,
            -0.00001531, -0.01294668, 100.46457166, 35999.37244981,
            102.93768193, 0.32327364, 0.0, 0.0),
    "mars": (1.52371034, 0.00001847, 0.09339410, 0.00007882,
             1.84969142, -0.00813131, -4.55343205, 19140.30268499,
             -23.94362959, 0.44441088, 49.55953891, -0.29257343),
    "jupiter": (5.20288700, -0.00011607, 0.04838624, -0.00013253,
                1.30439695, -0.00183714, 34.39644051, 3034.74612775,
                14.72847983, 0.21252668, 100.47390909, 0.20469106),
    "saturn": (9.53667594, -0.00125060, 0.05386179, -0.00050991,
               2.48599187, 0.00193609, 49.95424423, 1222.49362201,
               92.59887831, -0.41897216, 113.66242448, -0.28867794),
    "uranus": (19.18916464, -0.00196176, 0.04725744, -0.00004397,
               0.77263783, -0.00242939, 313.23810451, 428.48202785,
               170.95427630, 0.40805281, 74.01692503, 0.04240589),
    "neptune": (30.06992276, 0.00026291, 0.00859048, 0.00005105,
                1.77004347, 0.00035372, -55.12002969, 218.45945325,
                44.96476227, -0.32241464, 131.78422574, -0.00508664),
}

# Mass ratios M_body / M_sun (IAU/DE-series values)
_MASS_RATIO = {
    "mercury": 1.0 / 6023600.0,
    "venus": 1.0 / 408523.71,
    "emb": 1.0 / 328900.56,
    "mars": 1.0 / 3098708.0,
    "jupiter": 1.0 / 1047.3486,
    "saturn": 1.0 / 3497.898,
    "uranus": 1.0 / 22902.98,
    "neptune": 1.0 / 19412.24,
}
_MOON_EARTH_RATIO = 1.0 / 81.30056  # M_moon / M_earth


def _kepler_solve(M, e, iters=12):
    """Newton iteration for E − e sinE = M (host; always converges for
    planetary e < 0.25 with E0 = M)."""
    E = M + e * np.sin(M)
    for _ in range(iters):
        E = E - (E - e * np.sin(E) - M) / (1.0 - e * np.cos(E))
    return E


def _ecl_to_icrs(v):
    """Rotate ecliptic-J2000 → equatorial-J2000 (R1(−ε0))."""
    ce, se = np.cos(EPS0), np.sin(EPS0)
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    return np.stack([x, ce * y - se * z, se * y + ce * z], -1)


def _helio_pos(body, tdb_mjd):
    """Heliocentric ecliptic-J2000 position [au] of a planet/EMB."""
    (a0, ad, e0, ed, I0, Id, L0, Ld, w0, wd, O0, Od) = _ELEMENTS[body]
    t = (np.asarray(tdb_mjd, np.float64) - MJD_J2000) / 36525.0
    d2r = np.pi / 180.0
    a = (a0 + ad * t) * 1.0
    e = e0 + ed * t
    inc = (I0 + Id * t) * d2r
    L = (L0 + Ld * t) * d2r
    varpi = (w0 + wd * t) * d2r
    Om = (O0 + Od * t) * d2r
    w = varpi - Om  # argument of perihelion
    M = np.remainder(L - varpi, 2 * np.pi)
    E = _kepler_solve(M, e)
    xp = a * (np.cos(E) - e)
    yp = a * np.sqrt(1 - e * e) * np.sin(E)
    cw, sw = np.cos(w), np.sin(w)
    cO, sO = np.cos(Om), np.sin(Om)
    ci, si = np.cos(inc), np.sin(inc)
    x = (cw * cO - sw * sO * ci) * xp + (-sw * cO - cw * sO * ci) * yp
    y = (cw * sO + sw * cO * ci) * xp + (-sw * sO + cw * cO * ci) * yp
    z = (sw * si) * xp + (cw * si) * yp
    return np.stack([x, y, z], -1)


def _moon_geo_pos(tdb_mjd):
    """Geocentric Moon, ecliptic-J2000 [m] (Meeus truncated; λ precessed
    from of-date back to J2000 via −5029.0966″/cy)."""
    t = (np.asarray(tdb_mjd, np.float64) - MJD_J2000) / 36525.0
    d2r = np.pi / 180.0
    Lp = (218.3164477 + 481267.88123421 * t) * d2r
    D = (297.8501921 + 445267.1114034 * t) * d2r
    M = (357.5291092 + 35999.0502909 * t) * d2r
    Mp = (134.9633964 + 477198.8675055 * t) * d2r
    F = (93.2720950 + 483202.0175233 * t) * d2r
    lon = Lp + d2r * (
        6.288774 * np.sin(Mp) + 1.274027 * np.sin(2 * D - Mp)
        + 0.658314 * np.sin(2 * D) + 0.213618 * np.sin(2 * Mp)
        - 0.185116 * np.sin(M) - 0.114332 * np.sin(2 * F))
    lat = d2r * (
        5.128122 * np.sin(F) + 0.280602 * np.sin(Mp + F)
        + 0.277693 * np.sin(Mp - F) + 0.173237 * np.sin(2 * D - F))
    r = 1e3 * (385000.56 - 20905.355 * np.cos(Mp)
               - 3699.111 * np.cos(2 * D - Mp) - 2955.968 * np.cos(2 * D)
               - 569.925 * np.cos(2 * Mp))
    # of-date → J2000 ecliptic longitude
    lon = lon - (5029.0966 / 3600.0) * d2r * t
    cl, sl = np.cos(lat), np.sin(lat)
    return np.stack([r * cl * np.cos(lon), r * cl * np.sin(lon),
                     r * sl], -1)


_sun_cache = {}


def _sun_wrt_ssb_ecl(tdb_mjd):
    """Sun wrt SSB, ecliptic-J2000 [m]: −Σ μ_i r_i / (1 + Σ μ_i).

    Memoized on the epoch array: every body queried at the same epochs
    shares one 8-planet Kepler-solve sweep (compute_posvels hits this
    with identical arrays for earth/sun/each planet)."""
    tdb_mjd = np.asarray(tdb_mjd, np.float64)
    key = (tdb_mjd.shape, tdb_mjd.tobytes())
    hit = _sun_cache.get(key)
    if hit is not None:
        return hit
    num = np.zeros(tdb_mjd.shape + (3,))
    mtot = 0.0
    for body, mu in _MASS_RATIO.items():
        num = num + mu * _helio_pos(body, tdb_mjd) * AU
        mtot += mu
    out = -num / (1.0 + mtot)
    if len(_sun_cache) > 8:
        _sun_cache.clear()
    _sun_cache[key] = out
    return out


def _pos_ssb_ecl(body, tdb_mjd):
    """Body wrt SSB, ecliptic-J2000 [m]."""
    tdb_mjd = np.asarray(tdb_mjd, np.float64)
    if body == "ssb":
        return np.zeros(tdb_mjd.shape + (3,))
    sun = _sun_wrt_ssb_ecl(tdb_mjd)
    if body == "sun":
        return sun
    if body in ("earth", "moon"):
        emb = _helio_pos("emb", tdb_mjd) * AU + sun
        moon_geo = _moon_geo_pos(tdb_mjd)
        f = _MOON_EARTH_RATIO / (1.0 + _MOON_EARTH_RATIO)
        earth = emb - f * moon_geo
        return earth if body == "earth" else earth + moon_geo
    if body == "emb":
        return _helio_pos("emb", tdb_mjd) * AU + sun
    return _helio_pos(body, tdb_mjd) * AU + sun


# NAIF-id and alias compatibility with SPKEphemeris — both providers must
# accept the same body designators (get_ephemeris silently substitutes one
# for the other).
_ID_TO_NAME = {
    0: "ssb", 1: "mercury", 2: "venus", 3: "emb", 4: "mars", 5: "jupiter",
    6: "saturn", 7: "uranus", 8: "neptune", 10: "sun", 301: "moon",
    399: "earth",
}
_ALIASES = {
    "jupiter_barycenter": "jupiter", "saturn_barycenter": "saturn",
    "uranus_barycenter": "uranus", "neptune_barycenter": "neptune",
}


def ssb_posvel(body, tdb_mjd, vel_dt_s: float = 60.0):
    """Position [m] and velocity [m/s] of `body` wrt the SSB in
    equatorial-J2000 (ICRS-aligned) coordinates at TDB MJD epoch(s).

    Velocity by central difference (±vel_dt_s); error ~1e-7 m/s for
    Earth — far below the ~mm/s needed for Doppler corrections.
    """
    if isinstance(body, (int, np.integer)):
        body = _ID_TO_NAME[int(body)]
    body = _ALIASES.get(body.lower(), body.lower())
    tdb_mjd = np.atleast_1d(np.asarray(tdb_mjd, np.float64))
    h = vel_dt_s / DAY
    p = _ecl_to_icrs(_pos_ssb_ecl(body, tdb_mjd))
    pp = _ecl_to_icrs(_pos_ssb_ecl(body, tdb_mjd + h))
    pm = _ecl_to_icrs(_pos_ssb_ecl(body, tdb_mjd - h))
    v = (pp - pm) / (2 * vel_dt_s)
    return p, v
