"""Solar-system ephemerides.

Replaces astropy.coordinates.solar_system + jplephem (SURVEY.md §2b;
reference: src/pint/solar_system_ephemerides.py objPosVel_wrt_SSB).

Two providers:

- ``kepler`` (default, built-in): analytic Keplerian planetary theory +
  truncated lunar theory. Internally consistent (simulate→fit exact) but
  ~tens of ms absolute Roemer accuracy vs the real solar system — fine
  for framework validation, NOT for publication-grade real data.
- ``spk``: binary SPK/DAF kernel reader + Chebyshev evaluation for
  user-supplied JPL DE kernels (de440.bsp etc.) — no kernel ships in this
  zero-egress build (disk verified empty of .bsp).

`get_ephemeris(name)` returns a provider; names "DE440" etc. resolve to a
kernel file if one has been registered/found, else fall back to the
analytic provider with a loud warning.
"""

import os
import warnings

from pint_tpu.ephemeris import kepler as _kepler


class AnalyticEphemeris:
    """Built-in analytic provider (see module docstring for accuracy)."""

    name = "analytic-kepler"

    def ssb_posvel(self, body, tdb_mjd):
        return _kepler.ssb_posvel(body, tdb_mjd)


_REGISTRY = {}


def register_kernel(name, path):
    """Register an SPK kernel file for `name` (e.g. 'DE440')."""
    from pint_tpu.ephemeris.spk import SPKEphemeris

    _REGISTRY[name.upper()] = SPKEphemeris(path)


def get_ephemeris(name=None):
    """Resolve an ephemeris by name ('DE440', ...) or return the default
    analytic provider. Checks $PINT_TPU_EPHEM_DIR for '<name>.bsp'."""
    if name:
        key = str(name).upper()
        if key in _REGISTRY:
            return _REGISTRY[key]
        from pint_tpu import config

        ephem_dir = config.ephem_dir()
        if ephem_dir is not None:
            cand = os.path.join(str(ephem_dir), f"{key.lower()}.bsp")
            if os.path.exists(cand):
                register_kernel(key, cand)
                return _REGISTRY[key]
        warnings.warn(
            f"No SPK kernel available for ephemeris {name!r} (zero-egress "
            "build, no .bsp on disk); falling back to the built-in "
            "analytic Kepler ephemeris — internally consistent but only "
            "~arcmin-level absolute accuracy. Set $PINT_TPU_EPHEM_DIR or "
            "call register_kernel() for real-data work.",
            stacklevel=2,
        )
    return AnalyticEphemeris()
