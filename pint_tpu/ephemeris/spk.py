"""Binary SPK (DAF) ephemeris kernel reader + Chebyshev evaluation.

Replaces jplephem (SURVEY.md §2b): a pure-numpy DAF/SPK decoder for JPL
DE kernels (de421/de430/de440 .bsp), supporting segment types 2 (position
Chebyshev) and 3 (position+velocity Chebyshev), which cover all DE-series
planetary kernels.

DAF layout (NAIF "Double Precision Array File"):
- 1024-byte records; file record holds ND/NI/FWARD/BWARD and endianness
  tag ("LTL-IEEE"/"BIG-IEEE").
- Summary records: linked list from FWARD; 3 control doubles (NEXT, PREV,
  NSUM) then NSUM summaries of ND doubles + NI int32s.
- SPK summary: (et_begin, et_end) doubles; (target, center, frame, type,
  start_word, end_word) ints; words are 1-based double offsets.
- Type 2/3 segment tail: (INIT, INTLEN, RSIZE, N); N records of RSIZE
  doubles: MID, RADIUS, then per-component Chebyshev coefficients.

Coefficients are loaded once into contiguous arrays → Chebyshev
evaluation is vectorized numpy on host (and trivially jittable later for
on-device photon barycentering).
"""

from __future__ import annotations

import numpy as np

SSB = 0
SUN = 10
EMB = 3
EARTH = 399
MOON = 301

_NAIF_IDS = {
    "ssb": 0, "mercury": 1, "venus": 2, "emb": 3, "mars": 4,
    "jupiter": 5, "saturn": 6, "uranus": 7, "neptune": 8, "pluto": 9,
    "sun": 10, "moon": 301, "earth": 399,
    # barycenter aliases: DE kernels carry planet barycenters 1..9; for
    # giant planets the barycenter is the standard timing target
    "jupiter_barycenter": 5, "saturn_barycenter": 6,
}

# seconds TDB since J2000 epoch (SPK's ET) ↔ TDB MJD
_ET0_MJD = 51544.5
_SPD = 86400.0


class _Segment:
    __slots__ = ("target", "center", "frame", "dtype", "init", "intlen",
                 "rsize", "n", "coeffs", "mids", "radii", "ncomp", "degree",
                 "et0", "et1")

    def __init__(self, daf_words, summary):
        (et0, et1), (target, center, frame, dtype, start, end) = summary
        self.target, self.center, self.frame, self.dtype = (
            target, center, frame, dtype)
        self.et0, self.et1 = float(et0), float(et1)
        if dtype not in (2, 3):
            raise NotImplementedError(f"SPK segment type {dtype}")
        tail = daf_words[end - 4:end]
        self.init, self.intlen, rsize, n = tail
        self.rsize, self.n = int(rsize), int(n)
        data = daf_words[start - 1:start - 1 + self.rsize * self.n]
        recs = data.reshape(self.n, self.rsize)
        self.mids = recs[:, 0].copy()
        self.radii = recs[:, 1].copy()
        self.ncomp = 3 if dtype == 2 else 6
        self.degree = (self.rsize - 2) // self.ncomp
        # (n, ncomp, degree)
        self.coeffs = recs[:, 2:2 + self.ncomp * self.degree].reshape(
            self.n, self.ncomp, self.degree).copy()

    def eval(self, et):
        """Position [km] (and velocity [km/s]) at ET seconds (array).
        Caller guarantees et within [et0, et1] (enforced in SPKEphemeris).
        """
        et = np.asarray(et, np.float64)
        idx = np.clip(((et - self.init) // self.intlen).astype(np.int64),
                      0, self.n - 1)
        mid = self.mids[idx]
        rad = self.radii[idx]
        s = (et - mid) / rad  # in [-1, 1]
        c = self.coeffs[idx]  # (N, ncomp, deg)
        deg = self.degree
        s2 = (2 * s)[..., None]
        b0 = np.zeros(et.shape + (3,))
        b1 = np.zeros_like(b0)
        if self.ncomp == 6:
            # type 3 carries velocity coefficients directly — no
            # derivative recurrence needed
            for k in range(deg - 1, 0, -1):
                b0, b1 = c[..., :3, k] + s2 * b0 - b1, b0
            pos = c[..., :3, 0] + s[..., None] * b0 - b1
            bv0 = np.zeros_like(b0)
            bv1 = np.zeros_like(b0)
            for k in range(deg - 1, 0, -1):
                bv0, bv1 = c[..., 3:, k] + s2 * bv0 - bv1, bv0
            vel = c[..., 3:, 0] + s[..., None] * bv0 - bv1
        else:
            # Clenshaw for T_k plus derivative accumulation for velocity
            d0 = np.zeros_like(b0)
            d1 = np.zeros_like(b0)
            for k in range(deg - 1, 0, -1):
                ck = c[..., :3, k]
                b0, b1 = ck + s2 * b0 - b1, b0
                d0, d1 = 2 * b1 + s2 * d0 - d1, d0
            pos = c[..., :3, 0] + s[..., None] * b0 - b1
            vel = (b0 + s[..., None] * d0 - d1) / rad[..., None]
        return pos, vel


class SPKEphemeris:
    """A loaded SPK kernel; resolves (target wrt SSB) chains.

    API matches AnalyticEphemeris: ssb_posvel(body, tdb_mjd) → m, m/s in
    ICRS (DE kernels are ICRS/J2000-frame).
    """

    name = "spk"

    def __init__(self, path):
        self.path = path
        words, summaries = _read_daf(path)
        self.segments = [_Segment(words, s) for s in summaries]
        self._by_target = {}
        for seg in self.segments:
            self._by_target.setdefault(seg.target, []).append(seg)

    def _posvel_wrt(self, target, et):
        """Walk center chain target → SSB; km, km/s. Per-epoch segment
        selection by time coverage; epochs outside every segment raise
        (no silent Chebyshev extrapolation)."""
        pos = np.zeros(et.shape + (3,))
        vel = np.zeros_like(pos)
        body = target
        hops = 0
        while body != SSB:
            segs = self._by_target.get(body)
            if not segs:
                raise KeyError(
                    f"kernel {self.path} has no segment for body {body}")
            covered = np.zeros(et.shape, dtype=bool)
            center = segs[0].center
            for seg in segs:
                if seg.center != center:
                    raise NotImplementedError(
                        f"body {body}: segments with mixed centers")
                m = (~covered) & (et >= seg.et0) & (et <= seg.et1)
                if not m.any():
                    continue
                p, v = seg.eval(et[m])
                pos[m] += p
                vel[m] += v
                covered |= m
            if not covered.all():
                bad = et[~covered]
                raise ValueError(
                    f"kernel {self.path}: body {body} has no coverage for "
                    f"ET in [{bad.min():.0f}, {bad.max():.0f}] s past J2000 "
                    f"(kernel spans [{min(s.et0 for s in segs):.0f}, "
                    f"{max(s.et1 for s in segs):.0f}])")
            body = center
            hops += 1
            if hops > 10:
                raise RuntimeError("SPK center chain does not reach SSB")
        return pos, vel

    def ssb_posvel(self, body, tdb_mjd):
        if isinstance(body, (int, np.integer)):
            body_id = int(body)
        else:
            try:
                body_id = _NAIF_IDS[str(body).lower()]
            except KeyError:
                raise KeyError(
                    f"unknown body {body!r}; known: {sorted(_NAIF_IDS)}"
                ) from None
        tdb_mjd = np.atleast_1d(np.asarray(tdb_mjd, np.float64))
        et = (tdb_mjd - _ET0_MJD) * _SPD
        pos, vel = self._posvel_wrt(body_id, et)
        return pos * 1e3, vel * 1e3  # km → m


def _read_daf(path):
    """Return (word array: f64 view of whole file, SPK summaries)."""
    raw = np.fromfile(path, dtype=np.uint8)
    header = raw[:1024].tobytes()
    locidw = header[:8].decode("ascii", "replace")
    if not locidw.startswith("DAF/SPK"):
        raise ValueError(f"{path}: not an SPK DAF (LOCIDW={locidw!r})")
    locfmt = header[88:96].decode("ascii", "replace")
    if locfmt.startswith("BIG"):
        i4, f8 = ">i4", ">f8"
    else:
        i4, f8 = "<i4", "<f8"
    nd = int(np.frombuffer(header, i4, 1, 8)[0])
    ni = int(np.frombuffer(header, i4, 1, 12)[0])
    fward = int(np.frombuffer(header, i4, 1, 76)[0])
    if (nd, ni) != (2, 6):
        raise ValueError(f"{path}: unexpected DAF ND/NI = {nd}/{ni}")
    # reinterpret in place — no second copy of a ~100 MB kernel
    nwords = raw.size // 8
    words = raw[:nwords * 8].view(np.dtype(f8))
    if f8.startswith(">") and np.little_endian or \
       f8.startswith("<") and not np.little_endian:
        words = words.astype(np.float64)  # byteswap copy only if needed
    else:
        words = np.ascontiguousarray(words)
    summaries = []
    rec = fward
    ss = nd + (ni + 1) // 2  # summary size in doubles
    while rec > 0:
        base = (rec - 1) * 128  # record start in words
        nxt, _prev, nsum = words[base:base + 3]
        for i in range(int(nsum)):
            off = base + 3 + i * ss
            dbl = words[off:off + nd]
            # decode packed int32 pairs from the ORIGINAL bytes — the
            # native `words` array may have been lane-byteswapped, which
            # would scramble int32 order within each 8-byte word
            bo = (off + nd) * 8
            ints = np.frombuffer(
                raw[bo:bo + (ss - nd) * 8].tobytes(), dtype=i4)[:ni]
            summaries.append(((float(dbl[0]), float(dbl[1])),
                              tuple(int(x) for x in ints)))
        rec = int(nxt)
    return words, summaries
