"""Fitters: weighted least squares (WLS) and the downhill wrapper.

Reference: src/pint/fitter.py (Fitter, WLSFitter, DownhillFitter family;
GLSFitter lives in pint_tpu.gls once noise models land). The linear
solve is one jitted XLA kernel (SVD with singular-value thresholding,
exactly the reference's scaled-design-matrix solve); residual/design
evaluation reuses the model's compiled phase function.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.residuals import Residuals

__all__ = ["Fitter", "WLSFitter", "DownhillWLSFitter", "fit_summary",
           "ConvergenceFailure", "MaxiterReached", "StepProblem"]


class DegeneracyWarning(UserWarning):
    """The normal matrix was singular or ill-conditioned enough that
    the Cholesky solve failed and the SVD fallback (which drops
    near-degenerate directions) was used (reference: fitter.py
    DegeneracyWarning)."""


def warn_degenerate(what: str = "normal matrix") -> None:
    """Emit the shared Cholesky-failed/SVD-fallback DegeneracyWarning
    (one message, one stacklevel, used by GLS and wideband solvers)."""
    import warnings

    warnings.warn(
        f"{what} Cholesky failed (degenerate design columns?); "
        f"using the SVD fallback", DegeneracyWarning, stacklevel=4)


class ConvergenceFailure(RuntimeError):
    pass


class MaxiterReached(ConvergenceFailure):
    pass


class StepProblem(ConvergenceFailure):
    pass


@partial(jax.jit, static_argnames=("threshold_arg",))
def _wls_solve(M, r, err_s, threshold_arg=None):
    """min ||(r − Mx)/σ||²: column-normalized SVD solve.

    Returns (x, cov, chi2_post_linear). Mirrors the reference
    WLSFitter.fit_toas: scale M by 1/σ rows and per-column norms, SVD,
    zero singular values below threshold·s_max.
    """
    w = 1.0 / err_s
    # two-stage column scaling: F1/F2 columns reach ~1e13 s/unit, so
    # sum((M*w)^2) would exceed the exponent range of TPU-emulated f64
    # (f32-range limited); divide by the overflow-safe column max first
    colmax = jnp.max(jnp.abs(M), axis=0)
    colmax = jnp.where(colmax == 0, 1.0, colmax)
    Mw = (M / colmax[None, :]) * w[:, None]
    rw = r * w
    norm = jnp.sqrt(jnp.sum(Mw * Mw, axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = Mw / norm[None, :]
    U, s, Vt = jnp.linalg.svd(Mn, full_matrices=False)
    thresh = (threshold_arg if threshold_arg is not None
              else jnp.finfo(jnp.float64).eps * max(M.shape))
    keep = s > thresh * s[0]
    s_inv = jnp.where(keep, 1.0 / s, 0.0)
    x_n = Vt.T @ (s_inv * (U.T @ rw))
    x = x_n / colmax / norm
    cov_n = (Vt.T * (s_inv ** 2)[None, :]) @ Vt
    cov = cov_n / jnp.outer(colmax, colmax) / jnp.outer(norm, norm)
    resid_post = rw - Mn @ x_n
    chi2_post = jnp.sum(resid_post ** 2)
    return x, cov, chi2_post


def _wls_solve_np(M, r, err_s, threshold=None):
    """Pure-numpy mirror of _wls_solve — the supervised dispatch's
    host-failover path (identical two-stage scaling + thresholded
    SVD, scipy/numpy linalg)."""
    w = 1.0 / err_s
    colmax = np.max(np.abs(M), axis=0)
    colmax[colmax == 0] = 1.0
    Mw = (M / colmax[None, :]) * w[:, None]
    rw = r * w
    norm = np.sqrt(np.sum(Mw * Mw, axis=0))
    norm[norm == 0] = 1.0
    Mn = Mw / norm[None, :]
    U, s, Vt = np.linalg.svd(Mn, full_matrices=False)
    thresh = (threshold if threshold is not None
              else np.finfo(np.float64).eps * max(M.shape))
    keep = s > thresh * s[0]
    with np.errstate(divide="ignore"):
        s_inv = np.where(keep, 1.0 / np.where(s == 0, 1.0, s), 0.0)
    x_n = Vt.T @ (s_inv * (U.T @ rw))
    x = x_n / colmax / norm
    cov_n = (Vt.T * (s_inv ** 2)[None, :]) @ Vt
    cov = cov_n / np.outer(colmax, colmax) / np.outer(norm, norm)
    resid_post = rw - Mn @ x_n
    return x, cov, float(np.sum(resid_post ** 2))


class Fitter:
    """Base fitter: parameter bookkeeping + the fit_toas contract
    (reference: Fitter)."""

    def __init__(self, toas, model, residuals=None, track_mode=None):
        self.toas = toas
        self.model = model
        self.track_mode = track_mode
        self.resids_init = residuals or Residuals(toas, model,
                                                  track_mode=track_mode)
        self.resids = self.resids_init
        self.parameter_covariance_matrix = None
        self.errors: Dict[str, float] = {}
        self.converged = False
        self.stats = None  # FitStats, set by fit_toas

    def _solve_scope(self):
        """Context manager scoping the jitted solve kernels: pins
        small problems to the host CPU backend when the default
        backend is an accelerator (config.solve_device — dispatch
        latency dwarfs a tiny solve; a 62-TOA WLS fit measured 3.4 s
        over the axon tunnel vs 6 ms on host). jnp.asarray of the
        solve inputs must happen inside the scope."""
        from pint_tpu.config import solve_scope

        return solve_scope(self.toas.ntoas)

    def _solve_pinned(self) -> bool:
        """True when _solve_scope pins this problem's solves to the
        host CPU (jax.default_backend() cannot tell: it reports the
        process default platform regardless of the device context)."""
        from pint_tpu.config import solve_device

        return solve_device(self.toas.ntoas) is not None

    def _wls_dispatch(self, M, r, err_s, threshold):
        """The WLS solve routed through the runtime dispatch
        supervisor: watchdog deadline on accelerator backends, host
        numpy-mirror failover when the backend is timed out, broken
        or breaker-open (pint_tpu.runtime). Placement (jnp.asarray)
        happens INSIDE the dispatched closure: an H2D transfer to a
        wedged tunnel hangs exactly like a dispatch, so it must ride
        the same watchdog; for a pinned solve the closure runs inline
        on the caller thread, where the thread-local device scope
        applies."""
        from pint_tpu.runtime import get_supervisor

        M_h, r_h, e_h = (np.asarray(M), np.asarray(r),
                         np.asarray(err_s))

        def run():
            with self._solve_scope():
                return _wls_solve(jnp.asarray(M_h), jnp.asarray(r_h), jnp.asarray(e_h), threshold_arg=threshold)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        from pint_tpu import obs

        with obs.span("wls.solve", ntoa=self.toas.ntoas):
            return get_supervisor().dispatch(
                run, key="wls.solve", pinned=self._solve_pinned(),
                fallback=lambda: _wls_solve_np(M_h, r_h, e_h,
                                               threshold))

    def _record_stats(self, chi2: float, iterations: int, t0: float,
                      dof=None):
        """Populate self.stats (SURVEY §5 metrics requirement).
        ``dof`` overrides the TOA-residual dof for fitters whose chi2
        sums over more measurements (wideband: stacked TOA+DM)."""
        from pint_tpu.profiling import FitStats

        wall = time.perf_counter() - t0
        n = self.toas.ntoas
        if dof is None:
            dof = getattr(self.resids, "dof",
                          n - len(self.model.free_params))
        self.stats = FitStats(
            fitter=type(self).__name__, ntoa=n,
            nfree=len(self.model.free_params), dof=dof,
            chi2=float(chi2),
            reduced_chi2=float(chi2) / dof if dof else float("nan"),
            iterations=iterations, converged=self.converged,
            wall_time_s=wall,
            toas_per_sec=n * max(1, iterations) / wall if wall else 0.0)
        return self.stats

    @staticmethod
    def auto(toas, model, downhill=True, device=None, serve=None,
             streaming=None, **kw):
        """Pick a fitter from model contents and data (reference:
        Fitter.auto): wideband when TOAs carry -pp_dm DM channels, GLS
        when correlated-noise components are present, WLS otherwise;
        downhill wrappers by default.

        ``streaming`` selects the matrix-free StreamingGLSFitter
        (chunked normal-equation accumulation + preconditioned CG,
        ISSUE 12) whose peak device memory is O(chunk + (p+q)^2) —
        the million-TOA path. Default: auto-on for narrowband
        downhill fits at or above the ``config.solve_streaming`` TOA
        threshold ($PINT_TPU_STREAM_MIN_TOA, default 200k; 0
        disables), where the dense (N, p+q) whitened design stops
        being a sane device allocation; explicit True/False
        overrides. An explicit ``device=True`` wins over the auto
        route (never over ``streaming=True``).

        ``serve`` routes the fit through a running
        ``pint_tpu.serve.ServeEngine``: the returned ServeGLSFitter
        submits each iteration as a FitStepRequest, so this fit's
        solves coalesce with whatever else the engine is batching
        (the serving deployment's fit path — one padded vmapped
        dispatch amortizes the RTT across concurrent fits).

        ``device`` selects the DeviceDownhillGLSFitter — whole
        downhill fits as one jitted kernel per trial. Default: auto-on
        when the process backend is TPU and the model supports the
        anchored step (there the host fitters' exact-dd surfaces pin
        to the CPU backend, so the device fitter is both the fastest
        AND the most TPU-native path); explicit True/False overrides.
        On accelerator backends the device fitter additionally runs
        in WHOLE-FIT mode by default (config.whole_fit_enabled /
        $PINT_TPU_WHOLE_FIT): damping, acceptance and convergence all
        execute inside one donated, deadline-supervised lax.while_loop
        dispatch, so an entire downhill fit pays ONE dispatch RTT —
        pass ``whole_fit=``/``pipeline=`` through ``**kw`` to
        override per fitter."""
        import jax

        from pint_tpu.wideband import has_wideband_dm

        if serve is not None:
            if device:
                raise ValueError(
                    "serve= and device=True are exclusive: the serve "
                    "path batches solves across requests, the device "
                    "path chains iterations within one request")
            if has_wideband_dm(toas):
                raise ValueError(
                    "serve= cannot fit wideband TOAs: the batched "
                    "serve solve has no [time; DM] stacked system — "
                    "dropping the DM channels silently would corrupt "
                    "the fit. Use Fitter.auto without serve=")
            from pint_tpu.serve import ServeGLSFitter

            return ServeGLSFitter(toas, model, engine=serve, **kw)
        wideband = has_wideband_dm(toas)
        if streaming is None:
            from pint_tpu.config import solve_streaming

            thresh = solve_streaming()
            streaming = (downhill and not wideband and device is not
                         True and thresh > 0
                         and toas.ntoas >= thresh)
        if streaming:
            if wideband:
                raise ValueError(
                    "streaming=True cannot fit wideband TOAs (the "
                    "streaming accumulator has no stacked [time; DM] "
                    "system); use the dense wideband fitters")
            from pint_tpu.gls import StreamingGLSFitter

            return StreamingGLSFitter(toas, model, **kw)
        if device and not downhill:
            raise ValueError(
                "device=True requires downhill=True: the device fit "
                "path IS a downhill loop (use build_fit_step directly "
                "for single linearized solves)")
        if device is None:
            from pint_tpu.config import solve_device
            from pint_tpu.runtime import breaker_for

            device = (downhill
                      and jax.default_backend() == "tpu"
                      and model.supports_anchored()
                      # tiny problems route to host fitters whose
                      # solves pin to the CPU backend (_solve_scope):
                      # dispatch latency dwarfs the compute
                      and solve_device(toas.ntoas) is None
                      # an OPEN circuit breaker means the backend is
                      # wedged/dead: route new fits straight to the
                      # host fitters until a half-open probe closes it
                      and not breaker_for(
                          jax.default_backend()).is_open)
        if device and downhill:
            from pint_tpu.gls import DeviceDownhillGLSFitter

            return DeviceDownhillGLSFitter(toas, model,
                                           wideband=wideband, **kw)
        if wideband:
            from pint_tpu.wideband_fitter import (
                WidebandDownhillFitter,
                WidebandTOAFitter,
            )

            cls = WidebandDownhillFitter if downhill else \
                WidebandTOAFitter
            return cls(toas, model, **kw)
        has_noise = any(
            getattr(c, "is_basis_noise", False)
            for c in model.components.values())
        if has_noise:
            from pint_tpu.gls import DownhillGLSFitter, GLSFitter

            cls = DownhillGLSFitter if downhill else GLSFitter
        else:
            cls = DownhillWLSFitter if downhill else WLSFitter
        return cls(toas, model, **kw)

    # -- shared plumbing ----------------------------------------------

    def get_fitparams(self) -> List[str]:
        return self.model.free_params

    def get_designmatrix(self):
        return self.model.designmatrix(self.toas, incoffset=True)

    def update_model(self, x: np.ndarray, names: List[str]):
        for name, dx in zip(names, x):
            if name == "Offset":
                continue
            self.model.get_param(name).add_delta(float(dx))
        self.model.invalidate_cache(params_only=True)

    def set_uncertainties(self, cov: np.ndarray, names: List[str]):
        self.parameter_covariance_matrix = cov
        sig = np.sqrt(np.diag(cov))
        for name, s in zip(names, sig):
            if name == "Offset":
                continue
            self.model.get_param(name).uncertainty = float(s)
            self.errors[name] = float(s)

    def print_summary(self):
        print(fit_summary(self))

    def fit_toas(self, maxiter=1, **kw):
        raise NotImplementedError


class WLSFitter(Fitter):
    """Weighted least squares via jitted SVD (reference: WLSFitter)."""

    def fit_toas(self, maxiter=1, threshold=None):
        t0 = time.perf_counter()
        chi2 = None
        for _ in range(max(1, maxiter)):
            self.resids = Residuals(self.toas, self.model,
                                    track_mode=self.track_mode)
            r = self.resids.time_resids
            err_s = self.toas.get_errors() * 1e-6
            M, names, units = self.get_designmatrix()
            x, cov, _ = self._wls_dispatch(M, r, err_s, threshold)
            # residual here is model-phase excess: r ≈ M·(θ−θ_true), so
            # the parameter correction is −x
            x = -np.asarray(x)
            self.update_model(x, names)
            self.set_uncertainties(np.asarray(cov), names)
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        chi2 = self.resids.chi2
        self.converged = True
        self._record_stats(chi2, max(1, maxiter), t0)
        return chi2


class DownhillWLSFitter(WLSFitter):
    """Step-halving line-search wrapper (reference: DownhillWLSFitter /
    DownhillFitter.fit_toas): accept a step only if chi2 improves, else
    retry with lambda/2; raise after exhausting maxiter."""

    def fit_toas(self, maxiter=20, threshold=None, min_lambda=1e-3,
                 required_chi2_decrease=1e-2):
        t0 = time.perf_counter()
        iterations = 0
        best_chi2 = Residuals(self.toas, self.model,
                              track_mode=self.track_mode).chi2
        converged = False
        for _ in range(maxiter):
            iterations += 1
            self.resids = Residuals(self.toas, self.model,
                                    track_mode=self.track_mode)
            r = self.resids.time_resids
            err_s = self.toas.get_errors() * 1e-6
            M, names, units = self.get_designmatrix()
            x, cov, _ = self._wls_dispatch(M, r, err_s, threshold)
            x = -np.asarray(x)  # see WLSFitter: correction is −solution
            lam = 1.0
            accepted = False
            while lam >= min_lambda:
                self.update_model(lam * x, names)
                new_chi2 = Residuals(self.toas, self.model,
                                     track_mode=self.track_mode).chi2
                if new_chi2 <= best_chi2 + 1e-12:
                    accepted = True
                    break
                self.update_model(-lam * x, names)  # undo
                lam /= 2.0
            if not accepted:
                converged = True  # cannot improve: at the minimum
                break
            improved = best_chi2 - new_chi2
            best_chi2 = new_chi2
            self.set_uncertainties(np.asarray(cov), names)
            if improved < required_chi2_decrease:
                converged = True
                break
        else:
            raise MaxiterReached(
                f"no convergence in {maxiter} downhill iterations")
        self.converged = converged
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        if self.parameter_covariance_matrix is None:
            self.set_uncertainties(np.asarray(cov), names)
        self._record_stats(best_chi2, iterations, t0)
        return best_chi2


def fit_summary(fitter: Fitter) -> str:
    """Human-readable post-fit report (reference:
    Fitter.print_summary)."""
    m = fitter.model
    res = fitter.resids
    lines = [
        f"Fitted model {m.name or '?'} with {type(fitter).__name__}",
        f"TOAs: {fitter.toas.ntoas}   free params: "
        f"{len(m.free_params)}   dof: {res.dof}",
        f"Post-fit weighted RMS: {res.rms_weighted() * 1e6:.4f} us",
        f"chi2: {res.chi2:.3f}   reduced chi2: {res.reduced_chi2:.4f}",
        "",
        f"{'PARAM':<12} {'VALUE':>24} {'UNCERTAINTY':>14} UNITS",
    ]
    for name in m.free_params:
        p = m.get_param(name)
        # the parameter's own formatters: sexagesimal for angles, with
        # the uncertainty in the same displayed units
        lines.append(f"{name:<12} {p._format_value():>24} "
                     f"{p._format_uncertainty():>14} {p.units}")
    return "\n".join(lines)
