"""Pulse-phase bookkeeping.

The reference keeps phase as an (int, frac) pair of longdoubles
(src/pint/phase.py Phase) so that ~1e10 turns of absolute phase never eat
the sub-ns fractional part. Here a phase is simply a ``DD`` (double-double
turns); ``Phase`` is a thin named wrapper exposing the same (int, frac)
decomposition, registered as a pytree so it flows through jit/vmap.
"""

from __future__ import annotations

from typing import NamedTuple

import jax

from pint_tpu.ops.dd import (
    DD,
    _as_dd,
    dd_add,
    dd_frac,
    dd_neg,
    dd_round,
    dd_sub,
    dd_to_f64,
)


class Phase(NamedTuple):
    """Absolute pulse phase in turns, carried as DD."""

    turns: DD

    @property
    def int(self) -> jax.Array:
        """Nearest-integer pulse number (f64-exact up to 2^53 turns)."""
        return dd_round(self.turns).hi

    @property
    def frac(self) -> jax.Array:
        """Signed fractional phase in [-0.5, 0.5] turns (f64; its own
        rounding error is ~1e-16 turns ≈ 1e-18 s at F0=61 Hz)."""
        return dd_to_f64(dd_frac(self.turns))

    @property
    def frac_dd(self) -> DD:
        return dd_frac(self.turns)

    def __add__(self, other):
        other = other.turns if isinstance(other, Phase) else _as_dd(other)
        return Phase(dd_add(self.turns, other))

    def __sub__(self, other):
        other = other.turns if isinstance(other, Phase) else _as_dd(other)
        return Phase(dd_sub(self.turns, other))

    def __neg__(self):
        return Phase(dd_neg(self.turns))


def phase_from_f64(x) -> Phase:
    return Phase(_as_dd(x))
