"""Logging setup (reference: src/pint/logging.py, which configures
loguru — not present in this stack, so this configures the stdlib
logging module with the same ergonomics: one-call setup, level
filtering, repeated-message dedup, and warnings capture)."""

from __future__ import annotations

import logging
import sys
import warnings
from typing import Optional

__all__ = ["setup", "log", "DedupFilter"]

log = logging.getLogger("pint_tpu")


class DedupFilter(logging.Filter):
    """Suppress exact-duplicate log messages after the first
    ``max_repeats`` occurrences (reference: pint.logging's
    onlyonce/dedup machinery)."""

    def __init__(self, max_repeats: int = 1):
        super().__init__()
        self.max_repeats = max_repeats
        self._counts: dict = {}

    def filter(self, record: logging.LogRecord) -> bool:
        key = (record.levelno, record.getMessage())
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        return n < self.max_repeats


_state = {"handler": None, "showwarning": None}


def setup(level: str = "INFO", sink=None, dedup: bool = True,
          capture_warnings: bool = True,
          fmt: Optional[str] = None) -> logging.Logger:
    """Configure the pint_tpu logger (reference: pint.logging.setup).
    Returns the logger; safe to call repeatedly."""
    if _state["handler"] is not None:
        log.removeHandler(_state["handler"])
    handler = logging.StreamHandler(sink or sys.stderr)
    handler.setFormatter(logging.Formatter(
        fmt or "%(asctime)s %(levelname)-7s %(name)s: %(message)s",
        datefmt="%H:%M:%S"))
    if dedup:
        handler.addFilter(DedupFilter())
    log.addHandler(handler)
    log.setLevel(getattr(logging, level.upper()))
    log.propagate = False
    _state["handler"] = handler
    if capture_warnings and _state["showwarning"] is None:
        _state["showwarning"] = warnings.showwarning

        def showwarning(message, category, filename, lineno,
                        file=None, line=None):
            log.warning("%s: %s", category.__name__, message)

        warnings.showwarning = showwarning
    return log
