"""Bayesian timing interface: lnprior / lnlikelihood / lnposterior /
prior_transform over a TimingModel + TOAs.

Reference: src/pint/bayesian.py (BayesianTiming). TPU-first redesign:
the likelihood is a pure jitted function of the free-parameter vector —
the dd phase chain, weighted-mean subtraction, and the noise-
marginalized Gaussian likelihood fuse into one XLA program — and a
vmapped batch evaluator scores whole walker populations/sample grids in
one device call (the reference evaluates one point at a time under
emcee).

With the noise hyperparameters held fixed (the reference's default
mode), the correlated-noise covariance C = N + F phi F^T is constant
across likelihood calls, so its Woodbury Cholesky factor and log-
determinant are computed once at construction; each call costs one
phase evaluation plus two small matmuls.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BayesianTiming", "build_batched_phase_eval"]

LN2PI = float(np.log(2.0 * np.pi))


def build_batched_phase_eval(model, toas):
    """(theta0, frac_fn): the shared sampling plumbing. ``frac_fn`` is
    a traceable function tl_eff -> fractional phase (f64, N), where
    tl_eff = tl0 + (theta - theta0) formed on the HOST — the parameter
    point enters only through the dd LOW word, so every representable
    theta evaluates exactly (putting theta in the hi word would
    quantize perturbations of large parameters to ulp(value), ~0.1
    sigma for F0 at typical MSP precision). theta0 carries .tl0 as an
    attribute-free second return: returns (theta0, tl0, frac_fn).
    Used by BayesianTiming and PhotonMCMCFitter."""
    phase_fn, _ = model._build_phase_fn()
    cache = model.get_cache(toas)
    free, frozen, th, tl, fh, fl = model._pack()
    batch = cache["batch"]
    sc = {k: v for k, v in cache.items() if k != "batch"}
    tl_j, fh_j, fl_j = map(jnp.asarray, (tl, fh, fl))
    th0 = np.asarray(th, dtype=np.float64)
    th0_j = jnp.asarray(th0)

    def frac_fn(tl_eff):
        from pint_tpu.ops.dd import dd_frac

        ph = phase_fn(th0_j, tl_eff, fh_j, fl_j, batch, sc)[0]
        f = dd_frac(ph)
        return f.hi + f.lo

    return th0, np.asarray(tl, dtype=np.float64), frac_fn


class BayesianTiming:
    """lnposterior machinery for sampling timing parameters (reference:
    bayesian.BayesianTiming)."""

    def __init__(self, model, toas):
        self.model = model
        self.toas = toas
        self.param_labels: List[str] = list(model.free_params)
        self.nparams = len(self.param_labels)
        self._priors = [model.get_param(p).prior
                        for p in self.param_labels]

        free = model._pack()[0]
        if free != self.param_labels:
            raise ValueError(
                "free_params / packed-parameter mismatch: "
                f"{sorted(set(free) ^ set(self.param_labels))}")
        f0 = float(model.F0.value)
        self.theta0, self._tl0, self._frac_fn = build_batched_phase_eval(
            model, toas)
        # local alias for the traced closures below; the attribute is
        # the shareable surface (sampling.SampledNoiseLikelihood
        # reuses it instead of re-running the phase-eval build)
        frac_fn = self._frac_fn

        nvec = jnp.asarray(model.scaled_toa_uncertainty(toas) ** 2)
        w = 1.0 / nvec
        n = toas.ntoas
        # ECORR rides the O(N) Sherman-Morrison segment path exactly as
        # in the fit step (one rank-1 downdate per observing epoch);
        # only the Fourier bases stay dense
        seg = model.noise_model_ecorr_segments(toas)
        if seg is not None:
            eid_np, jvar_np, exclude = seg
            eid = jnp.asarray(eid_np)
            nseg = len(jvar_np)
            s_seg = jax.ops.segment_sum(w, eid, num_segments=nseg)
            g = jnp.asarray(jvar_np) / (1.0 + jnp.asarray(jvar_np)
                                        * s_seg)
            logdet_ecorr = float(jnp.sum(jnp.log1p(
                jnp.asarray(jvar_np) * s_seg)))
        else:
            eid = g = None
            nseg = 1
            exclude = ()
            logdet_ecorr = 0.0
        F = model.noise_model_designmatrix(toas, exclude=exclude)
        # constant noise machinery (hyperparameters fixed during
        # timing-parameter sampling, as in the reference)
        logdet_n = float(jnp.sum(jnp.log(nvec))) + logdet_ecorr
        if F is None:
            self._lnnorm = -0.5 * logdet_n - 0.5 * n * LN2PI
            Fw = None
            Lf = None
            dS = None
            EF = None
        else:
            phi = jnp.asarray(
                model.noise_model_basis_weight(toas, exclude=exclude))
            Fj = jnp.asarray(F)
            Fw = Fj * w[:, None]
            # Sff = F^T N_eff^-1 F + phi^-1 with the ECORR downdate
            Sff = Fj.T @ Fw + jnp.diag(1.0 / phi)
            if eid is not None:
                EF = jax.ops.segment_sum(Fw, eid, num_segments=nseg)
                Sff = Sff - EF.T @ (g[:, None] * EF)
            else:
                EF = None
            # Jacobi-precondition before factorizing: raw Sff mixes
            # O(1) data terms with 1/phi priors up to ~1e25 and a bare
            # Cholesky loses ~4 digits of the quadratic form (see
            # pint_tpu.gls._gls_chi2_kernel)
            dS = jnp.sqrt(jnp.diagonal(Sff))
            Lf = jax.scipy.linalg.cho_factor(
                Sff / jnp.outer(dS, dS), lower=True)
            # logdet C = logdet N_eff + sum ln phi + logdet Sff,
            # logdet Sff = logdet Sp + 2 sum ln dS
            logdet = (logdet_n
                      + float(jnp.sum(jnp.log(phi)))
                      + 2.0 * float(jnp.sum(jnp.log(
                          jnp.diagonal(Lf[0]))))
                      + 2.0 * float(jnp.sum(jnp.log(dS))))
            self._lnnorm = -0.5 * logdet - 0.5 * n * LN2PI

        lnnorm = self._lnnorm

        # with an explicit PhaseOffset the sampled PHOFF replaces the
        # implicit mean removal — subtracting the mean here would make
        # PHOFF exactly inert in the likelihood (the same bug class
        # the fitters fix; see residuals.Residuals)
        demean = "PhaseOffset" not in self.model.components

        def lnlike_core(tl_eff):
            # tl_eff is a jit INPUT, not a captured constant, so XLA
            # cannot constant-fold the tiny low word away against th0
            # (see build_batched_phase_eval)
            frac = frac_fn(tl_eff)
            if demean:
                wmean = jnp.sum(frac * w) / jnp.sum(w)
                frac = frac - wmean
            r = frac / f0
            rCr = jnp.sum(r * r * w)
            if eid is not None:
                wr_seg = jax.ops.segment_sum(w * r, eid,
                                             num_segments=nseg)
                rCr = rCr - jnp.sum(g * wr_seg ** 2)
            if Fw is not None:
                bF = Fw.T @ r
                if EF is not None:
                    bF = bF - EF.T @ (g * wr_seg)
                bF = bF / dS
                rCr = rCr - bF @ jax.scipy.linalg.cho_solve(Lf, bF)
            return -0.5 * rCr + lnnorm

        # the raw (un-jitted) closure is the reusable traced surface:
        # pint_tpu.sampling composes it into the whole-chain-on-device
        # kernel, where it runs inside a lax.scan rather than as its
        # own dispatch
        self._lnlike_core_raw = lnlike_core
        self._lnlike_core = jax.jit(lnlike_core)
        self._lnlike_core_batch = jax.jit(jax.vmap(lnlike_core))

        def _tl_eff(theta):
            return jnp.asarray(
                self._tl0 + (np.asarray(theta, dtype=np.float64)
                             - self.theta0))

        self._lnlike = lambda theta: self._lnlike_core(_tl_eff(theta))
        self._lnlike_batch = lambda thetas: self._lnlike_core_batch(
            jnp.asarray(self._tl0[None, :]
                        + (np.asarray(thetas, dtype=np.float64)
                           - self.theta0[None, :])))

    # ------------------------------------------------------------ API

    def lnprior(self, theta) -> float:
        """Sum of per-parameter prior log-densities (reference:
        BayesianTiming.lnprior). None priors (improper flat) contribute
        exactly 0 and are skipped."""
        theta = np.atleast_1d(np.asarray(theta, dtype=np.float64))
        total = 0.0
        for p, x in zip(self._priors, theta):
            if p is not None:
                total += float(p.logpdf(x))
        return total

    def prior_transform(self, cube) -> np.ndarray:
        """Unit-cube -> parameter space via per-parameter ppf (for
        nested samplers; reference: BayesianTiming.prior_transform).
        Raises for parameters with improper (None) priors."""
        cube = np.atleast_1d(np.asarray(cube, dtype=np.float64))
        out = np.empty_like(cube)
        for k, (p, q) in enumerate(zip(self._priors, cube)):
            if p is None:
                raise ValueError(
                    f"parameter {self.param_labels[k]} has no proper "
                    "prior; set one for prior_transform")
            out[k] = float(p.ppf(q))
        return out

    def lnlikelihood(self, theta) -> float:
        """Noise-marginalized Gaussian log-likelihood (reference:
        BayesianTiming.lnlikelihood)."""
        return float(self._lnlike(jnp.asarray(theta,
                                              dtype=jnp.float64)))

    def lnposterior(self, theta) -> float:
        lp = self.lnprior(theta)
        if not np.isfinite(lp):
            return -np.inf
        return lp + self.lnlikelihood(theta)

    # batch/vmapped evaluation — one device call for a whole population

    def lnlikelihood_batch(self, thetas) -> np.ndarray:
        """(S,) log-likelihoods for an (S, nparams) sample batch in ONE
        vmapped device call (no reference equivalent)."""
        return np.asarray(self._lnlike_batch(
            jnp.asarray(thetas, dtype=jnp.float64)))

    def lnposterior_batch(self, thetas) -> np.ndarray:
        thetas = np.atleast_2d(np.asarray(thetas, dtype=np.float64))
        # priors vectorized per COLUMN over the batch (None = flat = 0)
        lp = np.zeros(len(thetas))
        for k, p in enumerate(self._priors):
            if p is not None:
                lp += np.asarray(p.logpdf(thetas[:, k]))
        out = np.full(len(thetas), -np.inf)
        ok = np.isfinite(lp)
        if np.any(ok):
            # evaluate the FULL fixed-shape batch (masking would change
            # the batch shape every step and force an XLA recompile per
            # distinct in-bounds count); out-of-bounds rows are simply
            # discarded
            ll = self.lnlikelihood_batch(thetas)
            out[ok] = lp[ok] + ll[ok]
        return out
