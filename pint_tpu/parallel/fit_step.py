"""One GLS/WLS fit iteration as a single pure jittable function, and
its mesh-sharded variant.

Reference: src/pint/fitter.py GLSFitter.fit_toas runs residuals →
designmatrix → solve as three host phases over numpy; here the whole
iteration — phase evaluation (dd), residual mean subtraction, jacfwd
design matrix, whitening, normal equations, Cholesky, chi2 — is ONE
XLA program. That is the unit the driver compile-checks (`entry`) and
the unit the benchmark times.

Sharding (SURVEY.md §5 long-context): the TOA axis is the sequence
axis. All (N, ...) inputs are block-sharded over the mesh's 'toa' axis;
XLA GSPMD inserts the psum/all-gather for the weighted mean, the
normal-equation reduction M^T N^-1 M (a ring-reduce over ICI — the
moral equivalent of ring attention for normal-equation assembly), and
the replicated (p+q)^2 Cholesky. Nothing in the model code mentions
devices: the same function runs single-chip or sharded depending only
on input shardings.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.ops.dd import DD, dd_add, dd_frac, dd_to_dd32
from pint_tpu.ops.dd import dd as dd_new

__all__ = ["build_fit_loop", "build_fit_step", "build_fit_parts",
           "build_sharded_fit_step", "toa_sharding"]


def _pad_to(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _resolve_f32(flag: Optional[bool], env_name: str) -> bool:
    """Shared f32/f64 mode resolution: explicit argument > env var
    (f32/f64) > auto (f32 on TPU — f64 there is software-emulated and
    bypasses the MXU/VPU fast paths — f64 elsewhere). The env read
    goes through the validated ``config.f32_mode`` parser (ISSUE 11
    satellite): an unrecognized value warns once and falls back to
    auto instead of silently doing so."""
    from pint_tpu.config import f32_mode

    mode = f32_mode(env_name, flag)
    if mode is not None:
        return mode
    return jax.default_backend() == "tpu"


def _use_f32_matmul(flag: Optional[bool]) -> bool:
    """Normal-equation matmul precision ($PINT_TPU_GLS_MATMUL): the
    equilibrated normal equations only need ~1e-7 relative accuracy,
    which HIGHEST-precision f32 MXU passes deliver."""
    return _resolve_f32(flag, "PINT_TPU_GLS_MATMUL")


def _use_anchored(flag: Optional[bool]) -> bool:
    """Anchored delta-phase evaluation ($PINT_TPU_ANCHORED): the host
    computes the exact reference phase once and the device evaluates
    only the small difference — no ~1e10-turn intermediate survives,
    so TPU's non-IEEE emulated f64 (~2^-48, which breaks the dd EFTs
    and leaves a ~100 ns error floor through the absolute-phase
    cancellation) delivers full residual accuracy. Auto-on on TPU."""
    return _resolve_f32(flag, "PINT_TPU_ANCHORED")


def _use_hybrid_jac(flag: Optional[bool]) -> bool:
    """Hybrid analytic/AD Jacobian ($PINT_TPU_HYBRID_JAC, default ON
    on every backend): params with closed-form design columns (DMX
    windows, JUMPs, Fourier amplitudes, glitch pieces, PHOFF — see
    TimingModel.linear_design_columns) are dropped from the jacfwd
    tangent set and their columns computed from local factors times
    one shared stage-sensitivity JVP. Exact partials, not
    approximations (equality oracle: tests/test_hybrid_jac.py)."""
    from pint_tpu.config import hybrid_jac_enabled

    return hybrid_jac_enabled(flag)


def _use_f32_jac(flag: Optional[bool]) -> bool:
    """Design-matrix (jacfwd) precision ($PINT_TPU_JAC).

    The f32 path evaluates the Jacobian by re-tracing the SAME phase
    chain with f32 inputs: dd ops degrade to dd32 (f32 pairs, ~2^-48 —
    the same effective precision TPU's software-emulated f64 delivers,
    at native VPU speed), and everything else runs plain f32. Design
    columns only need ~1e-6 relative accuracy (they feed equilibrated
    normal equations already computed in f32 on the MXU), while the
    residual path keeps the full-precision f64/dd chain."""
    return _resolve_f32(flag, "PINT_TPU_JAC")


def _tree_to32(tree):
    """Cast every f64 leaf of a pytree to f32, converting DD pairs via
    dd_to_dd32 (splitting, not truncating, so 48 bits survive)."""
    def conv(x):
        if isinstance(x, DD):
            return dd_to_dd32(x)
        x = jnp.asarray(x)
        return x.astype(jnp.float32) if x.dtype == jnp.float64 else x

    return jax.tree.map(conv, tree, is_leaf=lambda x: isinstance(x, DD))


def _split32(hi, lo=None):
    """Device-side f64(+f64) -> dd32 split: (f32 head, f32 remainder).
    Thin wrapper over dd_to_dd32 returning the pair unpacked."""
    d = dd_to_dd32(DD(hi, jnp.zeros_like(hi) if lo is None else lo))
    return d.hi, d.lo


def _build_fit_core(model, toas, pad_to: Optional[int] = None,
                    matmul_f32: Optional[bool] = None,
                    jac_f32: Optional[bool] = None,
                    anchored: Optional[bool] = None,
                    hybrid_jac: Optional[bool] = None,
                    wideband: bool = False,
                    health: Optional[bool] = None):
    """(step_fn, parts_fn, args, names, meta): step_fn is pure and
    jittable,

        step_fn(th, tl, fh, fl, batch, cache, F, phi, nvec, valid)
            -> (dparams, cov, chi2, resids)

    dparams is the GLS parameter correction aligned with the returned
    ``names`` (an implicit Offset column leads UNLESS the model has a
    PhaseOffset — PHOFF replaces it, and then ``resids`` are NOT
    mean-subtracted either: the fitted offset plays that role; check
    names[0] == "Offset" rather than assuming it). cov is the
    correction covariance, chi2 the basis-marginalized chi2 at the
    current point, resids the time residuals [s].

    ``valid`` is a 0/1 mask supporting padding of the TOA axis to a
    mesh-divisible length: padded rows carry weight 0 everywhere.

    With ``wideband`` the iteration solves the stacked [time; DM]
    system in the same single XLA program (reference:
    WidebandTOAFitter's joint solve): the DM channel's residuals
    (-pp_dm/-pp_dme flags) and jacobian ride extra rows whose noise
    is white (correlated bases and ECORR act on TOA rows only), and
    ``resids`` stays the N time residuals.
    """
    phase_fn, (free_names, frozen_names) = model._build_phase_fn()
    cache = model.get_cache(toas)
    free, frozen, th, tl, fh, fl = model._pack()
    if "F0" in free:
        f0_src = ("free", free.index("F0"))
    else:
        f0_src = ("frozen", frozen.index("F0"))
    # PHOFF replaces the implicit Offset column (reference semantics:
    # both at once are exactly collinear -> singular normal matrix)
    incoffset = "PhaseOffset" not in model.components
    noff = 1 if incoffset else 0

    batch = cache["batch"]
    sc = {k: v for k, v in cache.items() if k != "batch"}
    n = toas.ntoas
    f32mm = _use_f32_matmul(matmul_f32)
    jac32 = _use_f32_jac(jac_f32)
    # in-trace health taps (ISSUE 14): a STATIC build flag, resolved
    # once here like the precision routes — part of the compile key
    # (same discipline as donation), so disarmed step programs are
    # byte-identical to pre-health ones and arming never mixes with
    # the quantized K/chunk keys
    from pint_tpu.config import health_enabled

    health_on = health_enabled(health)

    # per-TOA PHASE-command offsets (tim -padd flags, turns): folded
    # into the device residual exactly where the host Residuals adds
    # them (before mean subtraction), so the device fitters cannot
    # silently ignore a PHASE command the host path honors. Constant
    # in the parameters, so the Jacobian paths are untouched.
    padd_np = np.array(toas.get_flag_value("padd", 0.0, float))
    has_padd = bool(np.any(padd_np != 0.0))
    if has_padd:
        sc = {**sc, "padd": jnp.asarray(padd_np)}

    # hybrid Jacobian: closed-form columns for the linear params, AD
    # tangents only for the rest (40 -> 11 tangents at the north-star
    # shape). Static split at build time (finalized after the scale
    # computation below — scaled params must stay on AD); column
    # values are computed per step at the current parameter point.
    lin_set = model.linear_design_names() \
        if _use_hybrid_jac(hybrid_jac) else set()

    if wideband:
        from pint_tpu.wideband import get_wideband_dm

        dm_meas_np, _ = get_wideband_dm(toas)
        # DMEFAC/DMEQUAD-scaled DM sigmas, matching DMResiduals
        dm_err_np = model.scaled_dm_uncertainty(toas)
        sc = {**sc, "wb_dm": jnp.asarray(dm_meas_np),
              "wb_dme": jnp.asarray(np.asarray(dm_err_np))}

        def dm_device(pv, batch_x, cache_x):
            return model.dm_total_device(pv, batch_x, cache_x["main"])

        # static column restriction for the DM-row Jacobian (only
        # meaningful under the hybrid-Jacobian regime; None = full AD)
        dm_idx = None
        if _use_hybrid_jac(hybrid_jac):
            dm_set = model.dm_affecting_free_params()
            idx = [i for i, nm in enumerate(free) if nm in dm_set]
            if len(idx) < len(free):
                dm_idx = np.asarray(idx, dtype=np.int32)

    # Per-free-param scale for the f32 Jacobian: F_i (i>=2) columns are
    # dt^{i+1}/(i+1)! and overflow f32 range from i=4; differentiating
    # w.r.t. u_i = F_i * 2^e instead keeps scaled columns ~O(dt). The
    # step's outputs are mapped back (dtheta = s*du) in f64. Scales are
    # powers of two so s32 == s64 exactly, and each exponent is chosen
    # inside the window where BOTH the scaled column stays in normal
    # f32 range AND the tangent seed s/(i+1)! inside the dd Horner
    # stays normal (TPU flushes subnormals to zero). When no window
    # exists (F8+ at decade spans) the whole step falls back to the
    # f64 Jacobian — correct, just slower.
    scale_np = np.ones(len(free))
    if jac32:
        import math

        mjd = np.asarray(batch.tdb_day) + np.asarray(batch.tdb_frac.hi)
        T = max(float(np.max(np.abs(mjd - model.ref_day))) * 86400.0, 1.0)
        L = math.log2(T)
        for i, nm in enumerate(free):
            p = model.get_param(nm)
            if getattr(p, "prefix", None) == "F" and \
                    getattr(p, "index", 0) >= 2:
                idx = p.index
                lf = math.log2(math.factorial(idx + 1))
                e_hi = 122.0 - lf              # tangent seed normal
                e_lo = (idx + 1) * L - lf - 120.0  # column in range
                if e_lo > e_hi:
                    jac32 = False
                    scale_np[:] = 1.0
                    break
                e = int(min(max(round(idx * L), math.ceil(e_lo), 0),
                            math.floor(e_hi), 126))
                scale_np[i] = 2.0 ** (-e)
    # "no explicit matmul setting" = the VALIDATED parser resolves
    # to auto (config.f32_mode, ISSUE 11 satellite): an unparsable
    # $PINT_TPU_GLS_MATMUL now warns and behaves like unset instead
    # of silently disabling the dtype coupling below
    from pint_tpu.config import f32_mode as _f32_mode

    if matmul_f32 is None and \
            _f32_mode("PINT_TPU_GLS_MATMUL") is None:
        # auto-resolution couples the matmul route to the FINAL
        # Jacobian dtype (after the F8+ scale-window fallback above
        # may have cleared jac32): f32 columns lose nothing to an
        # f32-HIGHEST Gram, and f64 accumulation of f32 columns costs
        # ~30% of the step on CPU. Safe under degeneracy — _gls_core
        # retries in f64 when the f32 Cholesky trips. Explicit
        # flag/env still wins.
        f32mm = f32mm or jac32

    # finalize the hybrid split: the hybrid columns are
    # d(phase)/d(theta) while AD columns are d(phase)/d(u) with
    # u = theta*scale, and the shared dp/cov unscaling assumes every
    # CLAIMED param has scale exactly 1 — so any param the f32
    # scale-window machinery touched (F-prefix index>=2 under jac32)
    # drops back to the AD tangent set
    lin_set = {nm for i, nm in enumerate(free)
               if nm in lin_set and scale_np[i] == 1.0}
    lin_names = [nm for nm in free if nm in lin_set]
    nl_idx_list = [i for i, nm in enumerate(free) if nm not in lin_set]
    nl_idx = np.asarray(nl_idx_list, dtype=np.int32)
    lin_set = frozenset(lin_names)

    # anchored delta-phase: host computes the exact reference once;
    # the step's (th, tl) arguments then carry the HOST-COMPUTED exact
    # delta theta - theta_ref (zeros in the returned args)
    anchored_on = _use_anchored(anchored) and model.supports_anchored()
    afn = None
    f0_ref = 0.0
    if anchored_on:
        try:
            anc_arrays, anc_static = model.build_anchor(toas)
            afn = model._build_anchored_fn(anc_static)
            new_f0_ref = anc_static["fref"][0]
        except Exception as e:  # pragma: no cover — defensive: on a
            # CPU backend the direct chain is equally exact, so an
            # unforeseen host-reference failure degrades gracefully;
            # on TPU the direct absolute-phase chain is NOT
            # trustworthy (non-IEEE emulated f64 — CLAUDE.md), so a
            # silent fallback would be a correctness downgrade:
            # re-raise there
            if jax.default_backend() == "tpu":
                raise
            from pint_tpu.logging import log

            log.warning(
                "anchored fit-step build failed (%r); falling back "
                "to the direct phase chain (exact on this backend)", e)
            anchored_on = False
        else:
            # commit only after every build step succeeded: a partial
            # failure must not leave stale anchor arrays riding the
            # cache through padding/sharding/f32 conversion
            sc = {**sc, "anchor": {k: jnp.asarray(v)
                                   for k, v in anc_arrays.items()}}
            f0_ref = new_f0_ref

    nvec_np = model.scaled_toa_uncertainty(toas) ** 2
    # ECORR rides the Sherman-Morrison segment path (one rank-1
    # downdate per observing epoch) instead of dense basis columns —
    # see TimingModel.noise_model_ecorr_segments; only the remaining
    # bases (red/DM noise Fourier modes) stay dense
    seg = model.noise_model_ecorr_segments(toas)
    if seg is not None:
        eid_np, jvar_np, exclude = seg
    else:
        eid_np, jvar_np = np.zeros(n, np.int32), np.zeros(1)
        exclude = ()
    F_np = model.noise_model_designmatrix(toas, exclude=exclude)
    phi_np = model.noise_model_basis_weight(toas, exclude=exclude)
    if F_np is None:
        F_np, phi_np = np.zeros((n, 0)), np.ones(0)
    nseg = len(jvar_np)
    if wideband:
        Fdm_np = model.noise_model_dm_designmatrix(toas,
                                                   exclude=exclude)
        sc = {**sc, "wb_Fdm": jnp.asarray(
            np.zeros((n, 0)) if Fdm_np is None else Fdm_np)}

    valid_np = np.ones(n)
    if pad_to is not None and pad_to > n:
        pad = pad_to - n

        def padn(x, fill=0.0):
            if x.ndim == 1:
                return np.concatenate([np.asarray(x),
                                       np.full(pad, fill)])
            w = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return np.pad(np.asarray(x), w)

        batch = jax.tree.map(
            lambda a: jnp.asarray(_pad_leaf(np.asarray(a), pad)), batch)
        sc = jax.tree.map(
            lambda a: (jnp.asarray(_pad_leaf(np.asarray(a), pad))
                       if np.asarray(a).ndim and
                       np.asarray(a).shape[0] == n else jnp.asarray(a)),
            sc)
        F_np = padn(F_np)
        nvec_np = padn(nvec_np, fill=1.0)  # avoid 0-division; masked out
        valid_np = padn(valid_np)
        # padded rows carry w=0 so their segment routing is irrelevant;
        # route them to the zero-variance 'no epoch' slot (nseg-1) so
        # a time-sorted eid stays sorted through the padding
        eid_np = np.concatenate(
            [eid_np, np.full(pad, nseg - 1, np.int32)])

    def _assemble_jac(phase_of_u, u_full, lin_cols):
        """(N, nfree) Jacobian in free order: AD tangents only for the
        nonlinear subset (scattered into u_full so the closed-form
        params stay at their current values), closed-form columns for
        the rest."""
        if nl_idx_list:
            idx = jnp.asarray(nl_idx)

            def sub(u_nl):
                return phase_of_u(u_full.at[idx].set(u_nl))

            jac_nl = jax.jacfwd(sub)(u_full[idx])
        out, k = [], 0
        for nm in free:
            if nm in lin_set:
                out.append(lin_cols[nm])
            else:
                out.append(jac_nl[:, k])
                k += 1
        return jnp.stack(out, axis=1)

    def parts_fn(th, tl, fh, fl, batch, cache, F, phi, nvec, valid,
                 eid, jvar):
        """Design/residual ASSEMBLY half of the step: everything up
        to (but excluding) the normal-equation solve. Returns
        (M, Fv, r0, nvec', valid', eid', tmask) where r0 is the
        masked residual WITHOUT the weighted-mean subtraction (the
        streaming accumulator applies the mean correction post-hoc
        from accumulated scalars — exact algebra, see
        parallel/streaming.py) and tmask marks the valid TIME rows
        (the rows the mean subtraction acts on; zero on wideband DM
        rows). The primed outputs are the possibly [time; DM]-stacked
        versions of the inputs."""
        if anchored_on:
            def phase_f64(thx):
                fr, _ = afn(thx, tl, fh, fl, batch, cache)
                return fr
        else:
            def phase_f64(thx):
                ph, _ = phase_fn(thx, tl, fh, fl, batch, cache)
                # absolute-phase dd collapses to f64 AFTER the
                # fractional part is extracted — sub-ns residual
                # precision survives
                f = dd_frac(ph)
                return f.hi + f.lo

        frac = phase_f64(th)
        if has_padd:
            frac = frac + cache["padd"]
        i = f0_src[1]
        if anchored_on and f0_src[0] == "free":
            f0 = f0_ref + (th[i] + tl[i])  # th carries delta-theta
        else:
            f0 = (th[i] + tl[i]) if f0_src[0] == "free" \
                else (fh[i] + fl[i])
        # NOT mean-subtracted here: the step wrapper below subtracts
        # the weighted mean (incoffset models) so parts consumers can
        # accumulate the mean correction exactly instead
        r = frac / f0
        if jac32:
            # Jacobian via the f32/dd32 re-trace of the same phase
            # chain (see _use_f32_jac). Inputs split device-side so the
            # public step signature stays all-f64.
            batch32 = _tree_to32(batch)
            cache32 = _tree_to32(cache)
            s64 = jnp.asarray(scale_np)
            s32 = s64.astype(jnp.float32)
            ua, ub = _split32(th / s64, tl / s64)
            fa, fb = _split32(fh, fl)

            if anchored_on:
                def phase32(ua_):
                    fr, _ = afn(ua_ * s32, ub * s32, fa, fb,
                                batch32, cache32)
                    return fr
            else:
                def phase32(ua_):
                    ph, _ = phase_fn(ua_ * s32, ub * s32, fa, fb,
                                     batch32, cache32)
                    return ph.hi + ph.lo

            f032 = f0.astype(jnp.float32)
            valid32 = valid.astype(jnp.float32)
            if lin_names:
                lin_cols = model.linear_design_columns(
                    make_pv(ua * s32, ub * s32, fa, fb),
                    batch32, cache32, lin_set)
                jac = _assemble_jac(
                    phase32, ua, lin_cols) / f032
            else:
                jac = jax.jacfwd(phase32)(ua) / f032
            cols = [jac * valid32[:, None]]
            if incoffset:
                cols.insert(0, (valid32 / f032)[:, None])
            M = jnp.concatenate(cols, axis=1)
        else:
            if lin_names:
                lin_cols = model.linear_design_columns(
                    make_pv(th, tl, fh, fl), batch, cache, lin_set)
                jac = _assemble_jac(phase_f64, th, lin_cols) / f0
            else:
                jac = jax.jacfwd(phase_f64)(th) / f0
            cols = [jac * valid[:, None]]
            if incoffset:
                cols.insert(0, (valid / f0)[:, None])
            M = jnp.concatenate(cols, axis=1)
        r = r * valid
        Fv = F * valid[:, None]
        tmask = valid
        if wideband:
            # stacked [time; DM] rows: DM residuals in f64 (the
            # measurement scale needs it), DM jacobian in the same
            # dtype/scaling as the time jacobian
            def dm_of64(thx):
                return dm_device(make_pv(thx, tl, fh, fl),
                                 batch, cache)

            r_dm = (cache["wb_dm"] - dm_of64(th)) * valid

            def sparse_jac(fn, x):
                """DM-row Jacobian over only the DM-affecting columns
                (dm_idx, static): all other columns are structurally
                zero, so the tangent budget drops from n_free to
                len(dm_idx) (~40 -> ~13 at the north-star shape).
                With the hybrid split off, run the full jacfwd so the
                pure-AD oracle path stays byte-identical."""
                if dm_idx is None:
                    return jax.jacfwd(fn)(x)
                sub = jax.jacfwd(lambda xs: fn(x.at[dm_idx].set(xs)))(
                    x[dm_idx])
                return jnp.zeros((sub.shape[0], x.shape[0]),
                                 sub.dtype).at[:, dm_idx].set(sub)

            if jac32:
                def dm_of32(ua_):
                    return dm_device(
                        make_pv(ua_ * s32, ub * s32, fa, fb),
                        batch32, cache32)

                jac_dm = sparse_jac(dm_of32, ua)
                dm_cols = [-jac_dm * valid32[:, None]]
            else:
                jac_dm = sparse_jac(dm_of64, th)
                dm_cols = [-jac_dm * valid[:, None]]
            if incoffset:  # zero DM response of the offset column
                dm_cols.insert(0, jnp.zeros(
                    (jac_dm.shape[0], 1), jac_dm.dtype))
            M_dm = jnp.concatenate(dm_cols, axis=1)
            M = jnp.concatenate([M, M_dm], axis=0)
            r = jnp.concatenate([r, r_dm])
            nvec = jnp.concatenate([nvec, cache["wb_dme"] ** 2])
            # DM-process bases (PLDMNoise) couple into the DM rows;
            # all other bases are zero there
            Fv = jnp.concatenate(
                [Fv, cache["wb_Fdm"] * valid[:, None]], axis=0)
            tmask = jnp.concatenate([valid, jnp.zeros_like(valid)])
            valid = jnp.concatenate([valid, valid])
            # DM rows ride the zero-variance 'no epoch' ECORR slot
            eid = jnp.concatenate(
                [eid, jnp.full_like(eid, nseg - 1)])
        return M, Fv, r, nvec, valid, eid, tmask

    # jac32 column-scale unscaling vector (identity when jac32 off):
    # precomputed so the step wrapper and streaming finalize share it
    sfull_np = np.concatenate([np.ones(noff), scale_np])

    def step_fn(th, tl, fh, fl, batch, cache, F, phi, nvec, valid,
                eid, jvar):
        M, Fv, r0, nvec2, valid2, eid2, tmask = parts_fn(
            th, tl, fh, fl, batch, cache, F, phi, nvec, valid, eid,
            jvar)
        if incoffset:
            # weighted-mean subtraction over the valid time rows
            # (reference Residuals semantics; PHOFF models skip it —
            # the fitted offset plays that role)
            wt = tmask / nvec2
            r = r0 - (jnp.sum(r0 * wt) / jnp.sum(wt)) * tmask
        else:
            r = r0
        dp, cov, chi2, _ = _gls_core(
            M, Fv, phi, r, nvec2, valid2, eid2, jvar, nseg,
            f32mm=f32mm)
        if jac32:
            sfull = jnp.asarray(sfull_np)
            dp = dp * sfull
            cov = cov * jnp.outer(sfull, sfull)
        if not health_on:
            # time residuals only (first N rows of a wideband stack)
            return dp, cov, chi2, r[:valid.shape[0]]
        # in-trace health vector (ISSUE 14): three reductions riding
        # the existing dispatch — total non-finite count across the
        # step's outputs, max |whitened residual| in sigma over the
        # valid rows, and the step chi2. Costs O(N) elementwise work
        # fused into the program; compiled OUT entirely when the
        # static health flag is off.
        def nf(x):
            return jnp.sum(~jnp.isfinite(x)).astype(jnp.float64)

        hv = jnp.stack([
            nf(r) + nf(dp) + nf(chi2),
            jnp.max(jnp.abs(r) * tmask / jnp.sqrt(nvec2)),
            chi2.astype(jnp.float64),
        ])
        return dp, cov, chi2, r[:valid.shape[0]], hv

    # captured before the anchored zeroing below: the wideband DM
    # channel rebuilds pv as ref + delta in anchored mode
    th0_c, tl0_c = np.asarray(th).copy(), np.asarray(tl).copy()
    ref32_c = dd_to_dd32(DD(th0_c, tl0_c))

    def make_pv(thx, tlx, fhx, flx):
        """pv dict for auxiliary device channels (DM), honoring the
        anchored delta-theta convention and the caller's dtype."""
        if anchored_on:
            f32m = thx.dtype == jnp.float32
            rh = jnp.asarray(ref32_c.hi if f32m else th0_c)
            rl = jnp.asarray(ref32_c.lo if f32m else tl0_c)
            pv = {nm: dd_add(DD(rh[i], rl[i]), DD(thx[i], tlx[i]))
                  for i, nm in enumerate(free)}
        else:
            pv = {nm: DD(thx[i], tlx[i]) for i, nm in enumerate(free)}
        pv.update({nm: DD(fhx[j], flx[j])
                   for j, nm in enumerate(frozen)})
        return pv

    if anchored_on:
        # the (th, tl) slots carry delta theta vs the anchor: zero at
        # the reference point build_anchor just captured
        th, tl = np.zeros_like(th), np.zeros_like(tl)
    args = (jnp.asarray(th), jnp.asarray(tl), jnp.asarray(fh),
            jnp.asarray(fl), batch, sc, jnp.asarray(F_np),
            jnp.asarray(phi_np), jnp.asarray(nvec_np),
            jnp.asarray(valid_np), jnp.asarray(eid_np),
            jnp.asarray(jvar_np))
    meta = {"incoffset": incoffset, "nseg": nseg, "f32mm": f32mm,
            "jac32": jac32, "sfull": sfull_np,
            "anchored": anchored_on, "wideband": wideband,
            "has_ecorr": seg is not None, "health": health_on}
    return (step_fn, parts_fn, args,
            (["Offset"] if incoffset else []) + free, meta)


def build_fit_step(model, toas, **flags):
    """(step_fn, args, names) — the public one-XLA-program fit
    iteration (see ``_build_fit_core`` for the full contract).

    With ``health=True`` (or $PINT_TPU_HEALTH armed; ISSUE 14) the
    step returns a FIFTH output — the in-trace health vector
    ``[nonfinite_count, max_resid_sigma, chi2]`` — computed inside
    the same dispatch; disarmed (the default) the 4-tuple and the
    compiled program are byte-identical to pre-health builds (the
    flag is a static compile-key bit, like donation)."""
    step_fn, _, args, names, _ = _build_fit_core(model, toas, **flags)
    return step_fn, args, names


def build_fit_parts(model, toas, **flags):
    """(parts_fn, args, names, meta): the design/residual ASSEMBLY
    half of the fit step as its own pure jittable function — the unit
    the streaming normal-equation accumulator maps over fixed-size
    TOA chunks (``pint_tpu.parallel.streaming``). ``parts_fn`` takes
    the same 12 arguments as ``step_fn`` and returns
    ``(M, Fv, r0, nvec', valid', eid', tmask)`` with r0 the masked,
    NOT-mean-subtracted residuals; ``meta`` carries the static build
    facts (incoffset / nseg / f32mm / jac32 / the jac32 unscale
    vector ``sfull`` / anchored / has_ecorr) consumers need to finish
    the algebra exactly as ``step_fn`` would."""
    _, parts_fn, args, names, meta = _build_fit_core(model, toas,
                                                     **flags)
    return parts_fn, args, names, meta


def build_fit_loop(model, toas, max_iter: int = 8,
                   min_lambda: float = 1e-3,
                   required_chi2_decrease: float = 1e-2,
                   **step_flags):
    """Up to ``max_iter`` downhill GLS iterations — step-halving line
    search included — as ONE jittable device program, plus an exact
    replay ledger for the host.

    Motivation (measured, axon TPU v5e over the tunnel): every device
    dispatch pays a large fixed cost, so the one-round-trip-per-trial
    DeviceDownhillGLSFitter spends its wall time on dispatches, not
    math (62-TOA full WLS fit: 3.2 s on TPU vs 6 ms on CPU-XLA).
    Running K iterations per dispatch amortizes that fixed cost K-fold.
    Reference behavior mirrored: src/pint/fitter.py DownhillFitter
    (accept iff chi2 improves, else halve the step, stop at min_lambda
    or when the improvement is below ``required_chi2_decrease``).

    Precision contract: inside the loop the parameter state advances
    by two-sum on the (th, tl) pair — approximate on TPU's non-IEEE
    f64, exact on CPU — but every APPLIED update is recorded in a
    ledger of plain-f64 deltas, so the host replays the identical
    decision sequence in exact dd arithmetic afterward
    (DeviceDownhillGLSFitter.fit_toas(steps_per_dispatch=K)). In
    anchored mode (th, tl) carry small anchor-relative deltas, so the
    intra-loop two-sum error is bounded by 2^-48 of the DELTA, far
    inside the anchored error budget.

    Returns ``(loop_fn, args, names)`` where

        loop_fn(th, tl, fh, fl, batch, cache, F, phi, nvec, valid,
                eid, jvar, budget) -> (th', tl', dp, cov, best_chi2,
                                       chi2_0, niter, converged,
                                       deltas, lams, nevals)

    with ``deltas`` (max_iter, p) the applied parameter updates
    (zero rows beyond ``niter`` or on the rejected final iteration),
    ``lams`` (max_iter,) the accepted step-halving factors (0 =
    rejected/unused), ``chi2_0`` the chi2 of the entry point, and
    ``converged`` True when the loop stopped for a reason other than
    exhausting the iteration budget.

    ``budget`` is a RUNTIME iteration limit (int32 scalar; the
    returned ``args`` carry ``max_iter`` as the default): the loop
    stops at ``min(max_iter, budget)``, so ONE compiled program —
    ``max_iter`` stays quantized to the power-of-two compile keys of
    ``config.auto_steps_per_dispatch`` — serves every caller
    ``maxiter`` below it instead of forcing a fresh (multi-minute,
    remote) compile per distinct limit. This is what lets the
    whole-fit-on-device mode (``DeviceDownhillGLSFitter.fit_toas(
    whole_fit=True)``) reuse the K-chained executables: chaining is
    just the small-budget case of the same program.

    ``nevals`` counts the step_fn evaluations the loop actually
    executed (the entry step plus every line-search trial) — the
    denominator bench.py's ``dispatch_overhead`` block needs to
    separate pure step time from dispatch wall.

    The (th, tl) argument slots are DONATABLE: the loop's first two
    outputs (th', tl') have identical shape/dtype, so a caller that
    jits with ``donate_argnums=(0, 1)`` lets XLA alias the iterated
    parameter state in place instead of round-tripping fresh buffers
    through HBM every dispatch (the device fitter does exactly this
    when ``config.donation_enabled()``).
    """
    from jax import lax

    step_fn, _, args, names, loop_meta = _build_fit_core(
        model, toas, **step_flags)
    health_on = bool(loop_meta["health"])
    noff = 1 if names and names[0] == "Offset" else 0
    K = int(max_iter)

    def _two_sum_add(ah, al, d):
        # the host replay bump — dd_np.add(dd_np.dd(th, tl),
        # dd_np.dd(d)) — composed from the 1:1-mirrored jax dd
        # helpers, so on IEEE hardware the device trajectory and the
        # host ledger replay produce identical pairs by construction;
        # on TPU's non-IEEE f64 both degrade together to ~2^-48 of
        # the (small, anchored) delta
        s = dd_add(dd_new(ah, al), dd_new(d))
        return s.hi, s.lo

    def loop_fn(th, tl, fh, fl, batch, cache, F, phi, nvec, valid,
                eid, jvar, budget):
        def step(a, b):
            out = step_fn(a, b, fh, fl, batch, cache, F,
                          phi, nvec, valid, eid, jvar)
            # health (ISSUE 14): the static flag appends the
            # in-trace vector — disarmed, the tuple (and therefore
            # this whole loop program) is the pre-health one
            if health_on:
                return out[0], out[1], out[2], out[4]
            return out[0], out[1], out[2]

        out0 = step(th, tl)
        dp0, cov0, chi2_0 = out0[0], out0[1], out0[2]
        p = th.shape[0]
        deltas0 = jnp.zeros((K, p), th.dtype)
        lams0 = jnp.zeros(K, th.dtype)

        def cond(c):
            k, done = c[0], c[1]
            return jnp.logical_and(
                jnp.logical_not(done),
                jnp.logical_and(k < K, k < budget))

        def body(c):
            (k, done, thk, tlk, dpk, covk, best, deltas, lams,
             nev) = c[:10]
            d = dpk[noff:]

            def hcond(h):
                lam, acc = h[0], h[1]
                return jnp.logical_and(jnp.logical_not(acc),
                                       lam >= min_lambda)

            def hbody(h):
                lam, _, thc, tlc, dpc, covc, chic, nv = h[:8]
                tht, tlt = _two_sum_add(thk, tlk, lam * d)
                trial = step(tht, tlt)
                dpt, covt, chit = trial[0], trial[1], trial[2]
                ok = jnp.logical_and(jnp.isfinite(chit),
                                     chit <= best + 1e-12)
                keep = lambda new, old: jnp.where(ok, new, old)
                out = (jnp.where(ok, lam, lam / 2.0), ok,
                       keep(tht, thc), keep(tlt, tlc),
                       keep(dpt, dpc), keep(covt, covc),
                       keep(chit, chic), nv + 1)
                if health_on:
                    # the ACCEPTED trial's health vector (a rejected
                    # overshoot legitimately NaNs its chi2 — the
                    # line search's job, not an incident)
                    out = out + (keep(trial[3], h[8]),)
                return out

            hcarry = (jnp.asarray(1.0, th.dtype), jnp.asarray(False),
                      thk, tlk, dpk, covk,
                      jnp.asarray(jnp.inf, th.dtype), nev)
            if health_on:
                hcarry = hcarry + (c[10],)
            hout = lax.while_loop(hcond, hbody, hcarry)
            lam, acc, thc, tlc, dpc, covc, chic, nev = hout[:8]

            improved = best - chic
            applied = jnp.where(acc, lam * d, jnp.zeros_like(d))
            deltas = deltas.at[k].set(applied)
            lams = lams.at[k].set(jnp.where(acc, lam, 0.0))
            keep = lambda new, old: jnp.where(acc, new, old)
            done = jnp.logical_or(
                jnp.logical_not(acc),
                improved < required_chi2_decrease)
            out = (k + 1, done, keep(thc, thk), keep(tlc, tlk),
                   keep(dpc, dpk), keep(covc, covk),
                   keep(chic, best), deltas, lams, nev)
            if health_on:
                out = out + (keep(hout[8], c[10]),)
            return out

        carry = (jnp.asarray(0, jnp.int32),
                 jnp.asarray(False), th, tl, dp0, cov0,
                 chi2_0, deltas0, lams0,
                 jnp.asarray(1, jnp.int32))
        if health_on:
            carry = carry + (out0[3],)
        fin = lax.while_loop(cond, body, carry)
        (k, done, thf, tlf, dpf, covf, best, deltas, lams,
         nev) = fin[:10]
        out = (thf, tlf, dpf, covf, best, chi2_0, k, done, deltas,
               lams, nev)
        if health_on:
            # the accepted-state health vector rides as output 11 —
            # appended at the END so every pre-health index (out[4]
            # chi2, out[10] nevals, ...) is untouched
            out = out + (fin[10],)
        return out

    return loop_fn, args + (jnp.asarray(K, jnp.int32),), names


def _pad_leaf(a: np.ndarray, pad: int) -> np.ndarray:
    """Pad the TOA axis of a batch leaf by replicating the last row
    (zero-padding would put observers at the SSB origin and NaN the
    Shapiro log; replicated rows are real physics, masked out of every
    reduction by ``valid``). ToaBatch leaves are (N,), (N,3), or
    (P,N,3); 1-length TZR leaves are left alone."""
    if a.ndim == 0 or a.shape == (1,):
        return a
    if a.ndim == 3:
        return np.pad(a, [(0, 0), (0, pad), (0, 0)], mode="edge")
    return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1), mode="edge")


def _symm_mm(X, Y, f32: bool):
    """X.T @ Y with optional f32 inputs at HIGHEST matmul precision
    (on TPU: 6-pass bf16 through the MXU, ~f32-exact; f64 matmuls
    there are software-emulated and an order of magnitude slower).
    With f32=False inputs are UPCAST to f64 and accumulated there —
    an exactly-accumulated Gram matrix is PSD whatever the column
    quantization, which is what the degenerate-model retry in
    _gls_core relies on. Result is always f64."""
    if not f32:
        return X.astype(jnp.float64).T @ Y.astype(jnp.float64)
    out = jax.lax.dot(X.astype(jnp.float32).T, Y.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST)
    return out.astype(jnp.float64)


def _gls_core(M, F, phi, r, nvec, valid, eid, jvar, nseg: int,
              f32mm: bool = False):
    """The basis-Woodbury solve (same algebra as pint_tpu.gls), inlined
    so the whole iteration fuses into one XLA program.

    ECORR enters as the effective white covariance
        N_eff = diag(nvec) + sum_k jvar_k u_k u_k^T
    (u_k = indicator of epoch k). Each epoch block is rank-1, so
    Sherman-Morrison gives, for any vectors a,b:
        a^T N_eff^-1 b = a^T W b - sum_k g_k (u_k^T W a)(u_k^T W b),
        g_k = jvar_k / (1 + jvar_k s_k),  s_k = u_k^T w,  W = diag(w).
    The u_k^T W · contractions are segment-sums over ``eid`` — O(N)
    instead of carrying ~N/4 dense quantization columns through the
    normal equations (the reference's layout). Only the Fourier noise
    bases remain in F."""
    p = M.shape[1]
    mdt = M.dtype  # f32 when the Jacobian came from the f32 path: all
    # (N, p+q)-wide elementwise work then stays f32 (native VPU speed
    # on TPU), while (N,)-vectors and the (p+q)^2 solve stay f64
    w = valid / nvec
    wM = w.astype(mdt)
    F = F.astype(mdt)
    # Two-stage column normalization. The F1/F2 design columns reach
    # ~1e13 s/unit, so sum(M^2 * w) would hit ~1e38+ — beyond the
    # exponent range of TPU-emulated f64 (f32-range limited). Scaling
    # by the (overflow-safe) column max first keeps every intermediate
    # far from the range limit; the two factors are applied
    # sequentially on the way back out for the same reason.
    colmax = jnp.max(jnp.abs(M), axis=0)
    colmax = jnp.where(colmax == 0, 1.0, colmax)
    Ms = M / colmax[None, :]
    norm = jnp.sqrt(jnp.sum(Ms * Ms * wM[:, None], axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    big = jnp.concatenate([Mn, F], axis=1)
    # symmetric sqrt(w) split: keeps the f32-cast entries well-scaled
    # (big*w spans ~1e12 from the weights; big*sqrt(w) only ~1e6) and
    # makes Sigma exactly symmetric by construction
    sw = jnp.sqrt(w)
    swM = sw.astype(mdt)
    bigs = big * swM[:, None]
    rs = r * sw
    q = F.shape[1]
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi]) if q else \
        jnp.zeros(p)

    def assemble(use32: bool):
        Sigma = _symm_mm(bigs, bigs, use32)
        b = _symm_mm(bigs, rs.astype(mdt)[:, None], use32)[:, 0]
        rCr = jnp.sum(rs * rs)
        if nseg > 1:  # static: no ECORR -> skip the dead downdate
            # epoch contractions (Sherman-Morrison downdate); the
            # O(N p) segment sums stay f64 (elementwise, cheap) — only
            # the (nseg x p)^T (nseg x p) contraction rides the matmul
            # path. NOTE: no indices_are_sorted hint — eid is a
            # runtime argument of the advertised-pure step_fn, and a
            # baked-in sortedness promise would silently corrupt the
            # downdate for any caller substituting a re-ordered eid
            def seg(x):
                return jax.ops.segment_sum(x, eid, num_segments=nseg)

            s_seg = seg(w)
            g = jvar / (1.0 + jvar * s_seg)
            E = seg(big * wM[:, None])
            wr_seg = seg(w * r)
            sg = jnp.sqrt(g)
            Eg = E * sg.astype(mdt)[:, None]
            Sigma = Sigma - _symm_mm(Eg, Eg, use32)
            b = b - Eg.astype(jnp.float64).T @ (sg * wr_seg)
            rCr = rCr - jnp.sum(g * wr_seg ** 2)
        return Sigma + jnp.diag(prior), b, rCr

    def solve(Sigma, b, rCr):
        # Jacobi-precondition to unit diagonal: Sigma mixes O(1) data
        # terms with 1/phi priors up to ~1e25, and TPU f64 (emulated,
        # not IEEE-correctly-rounded) loses the Cholesky on that raw
        # scaling
        d = jnp.sqrt(jnp.diagonal(Sigma))
        d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
        cf = jax.scipy.linalg.cho_factor(Sigma / jnp.outer(d, d),
                                         lower=True)
        xhat = jax.scipy.linalg.cho_solve(cf, b / d) / d
        inv = jax.scipy.linalg.cho_solve(
            cf, jnp.eye(Sigma.shape[0])) / jnp.outer(d, d)
        # chi2 at the point: marginalize the noise (F-basis + ECORR)
        # only, not the parameter block (see gls.py _gls_chi2_kernel)
        if q:
            bF = b[p:]
            SF = Sigma[p:, p:]
            dF = d[p:]
            cfF = jax.scipy.linalg.cho_factor(SF / jnp.outer(dF, dF),
                                              lower=True)
            chi2 = rCr - bF @ (jax.scipy.linalg.cho_solve(
                cfF, bF / dF) / dF)
        else:
            chi2 = rCr
        return xhat, inv, chi2

    xhat, inv, chi2 = solve(*assemble(f32mm))
    if f32mm:
        # in-kernel degeneracy rescue: on a near-rank-deficient model
        # the f32-accumulated normal matrix can lose positive
        # definiteness (f32 rounding of a large cancellation) and the
        # Cholesky NaNs out. Retry ONCE with f64-accumulated matmuls —
        # an exactly-accumulated Gram matrix is PSD whatever the
        # column quantization — executing the slow branch only when
        # the fast one actually failed (lax.cond, not jnp.where).
        # "Failed" must cover finite-but-garbage outputs too: an
        # indefinite f32 Gram can pass the Cholesky with a tiny
        # positive pivot from rounding instead of producing a NaN, so
        # also require a finite inverse with the non-negative diagonal
        # any true covariance has (ADVICE r4).
        ok = (jnp.all(jnp.isfinite(xhat)) & jnp.isfinite(chi2)
              & jnp.all(jnp.isfinite(inv))
              & jnp.all(jnp.diagonal(inv) >= 0.0))
        xhat, inv, chi2 = jax.lax.cond(
            ok,
            lambda: (xhat, inv, chi2),
            lambda: solve(*assemble(False)))
    dparams = -xhat[:p] / colmax / norm  # r ≈ M(θ−θ_true): corr is −x
    cov = inv[:p, :p] / jnp.outer(colmax, colmax) / jnp.outer(norm, norm)
    return dparams, cov, chi2, r


# ---------------------------------------------------------------- mesh


def toa_sharding(mesh, axis: str = "toa"):
    """NamedSharding placing the leading (TOA) axis over ``axis``,
    replicating everything else."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def shard_leaf(a):
        a = jnp.asarray(a)
        if a.ndim == 0 or a.shape[0] == 1:
            return NamedSharding(mesh, P())
        if a.ndim == 3:  # (P, N, 3) planet stack: N is axis 1
            return NamedSharding(mesh, P(None, axis, None))
        return NamedSharding(
            mesh, P(axis, *([None] * (a.ndim - 1))))

    return shard_leaf


def build_sharded_fit_step(model, toas, mesh, axis: str = "toa",
                           **flags):
    """The same fit step, with all TOA-axis inputs block-sharded over
    ``mesh``'s ``axis``. Pads N to a mesh-divisible length with masked
    rows. Extra keyword flags (matmul_f32/jac_f32/anchored) pass
    through to build_fit_step. Returns (jitted_fn, device_args,
    names)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    nshard = mesh.shape[axis]
    pad_to = _pad_to(toas.ntoas, nshard)
    step_fn, _, args, names, smeta = _build_fit_core(
        model, toas, pad_to=pad_to, **flags)
    th, tl, fh, fl, batch, sc, F, phi, nvec, valid, eid, jvar = args

    shard = toa_sharding(mesh, axis)
    rep = NamedSharding(mesh, P())

    def place(tree, fn):
        return jax.tree.map(lambda a: jax.device_put(jnp.asarray(a),
                                                     fn(a)), tree)

    batch_s = place(batch, shard)
    # cache entries: shard those with a leading N axis, replicate rest
    n = pad_to

    def cache_sharding(a):
        a = jnp.asarray(a)
        if a.ndim >= 1 and a.shape[0] == n:
            return shard(a)
        return rep

    sc_s = place(sc, cache_sharding)
    dev_args = (
        jax.device_put(th, rep), jax.device_put(tl, rep),
        jax.device_put(fh, rep), jax.device_put(fl, rep),
        batch_s, sc_s,
        jax.device_put(F, shard(F)), jax.device_put(phi, rep),
        jax.device_put(nvec, shard(nvec)),
        jax.device_put(valid, shard(valid)),
        jax.device_put(eid, shard(eid)), jax.device_put(jvar, rep),
    )
    out_shardings = (rep, rep, rep, shard(jnp.zeros(n)))
    if smeta["health"]:
        # the in-trace health vector is a replicated 3-scalar output
        out_shardings = out_shardings + (rep,)
    jitted = jax.jit(step_fn, out_shardings=out_shardings)

    def supervised(*step_args):
        """The sharded step routed through the runtime dispatch
        supervisor (watchdog deadline on accelerator backends; a
        wedged tunnel returns DispatchTimeout instead of hanging the
        caller). Inline — zero overhead, device-resident outputs —
        on the plain CPU mesh; on a GUARDED accelerator dispatch the
        outputs come back as host numpy (the supervisor's worker
        performs the D2H read so the deadline covers completion —
        callers here all read to host immediately anyway). The raw
        jit object stays reachable as ``supervised.jitted`` for
        introspection (``.lower()``/cost analysis)."""
        from pint_tpu import obs
        from pint_tpu.runtime import get_supervisor

        with obs.span("fit_step.sharded"):
            return get_supervisor().dispatch(
                jitted, *step_args, key="fit_step.sharded")

    supervised.jitted = jitted
    return supervised, dev_args, names
