"""PTA-scale batch fitting: one vmapped GLS solve across many pulsars.

The reference has no intra-process parallelism beyond a process pool
(SURVEY.md §2c); per-pulsar independence is embarrassing parallelism.
Here each pulsar's linearized GLS problem (design matrix, residuals,
noise basis) is padded to a common (N_max, p_max, q_max) shape and the
whole batch is solved by ONE vmapped, jitted kernel — the pulsar axis
maps onto the mesh's 'pulsar' axis (DCN-friendly: zero cross-pulsar
communication, result gather only), matching BASELINE.md config #5.

Ragged shapes are handled with validity masks: padded TOA rows carry
zero weight, padded parameter columns are identity-pinned in the normal
matrix, padded basis columns get unit prior and zero data weight.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.residuals import Residuals

__all__ = ["PulsarProblem", "build_problem", "stack_problems",
           "pta_solve", "pta_solve_np", "fit_pta", "PTAFitResult"]


class PTAFitResult(list):
    """fit_pta's return: a list of per-pulsar results carrying the
    aggregate timing scoreboard in ``.stats``."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.stats: dict = {}


class PulsarProblem:
    """One pulsar's linearized GLS inputs (host, unpadded)."""

    def __init__(self, M, r, nvec, F, phi, names, model=None, toas=None):
        self.M = np.asarray(M)
        self.r = np.asarray(r)
        self.nvec = np.asarray(nvec)
        self.F = np.asarray(F)
        self.phi = np.asarray(phi)
        self.names = list(names)
        self.model = model
        self.toas = toas


def build_problem(toas, model, track_mode=None) -> PulsarProblem:
    """Assemble the linearized problem at the model's current point."""
    res = Residuals(toas, model, track_mode=track_mode)
    M, names, _ = model.designmatrix(toas, incoffset=True)
    nvec = model.scaled_toa_uncertainty(toas) ** 2
    F = model.noise_model_designmatrix(toas)
    phi = model.noise_model_basis_weight(toas)
    if F is None:
        F = np.zeros((toas.ntoas, 0))
        phi = np.ones(0)
    return PulsarProblem(np.asarray(M), np.asarray(res.time_resids),
                         nvec, F, phi, names, model=model, toas=toas)


def stack_problems(problems: Sequence[PulsarProblem],
                   shape: Optional[Tuple[int, int, int, int]] = None):
    """Pad every pulsar to the batch maxima and stack:
    returns dict of (P, ...) arrays.

    ``shape`` optionally fixes the padded target (P, N, pmax, qmax) —
    each component must be >= the batch's own maximum. The serve
    layer's shape-bucketing passes it so heterogeneous request batches
    land on a bounded set of compiled shapes instead of one shape per
    batch; extra batch slots beyond len(problems) are fully padded
    pulsars (valid = pvalid = 0, unit nvec/phi), which the masked
    kernel solves to the identity system (dparams 0, chi2 0)."""
    P = len(problems)
    N = max(p.M.shape[0] for p in problems)
    pmax = max(p.M.shape[1] for p in problems)
    qmax = max(p.F.shape[1] for p in problems)
    if shape is not None:
        Pt, Nt, pt, qt = shape
        if Pt < P or Nt < N or pt < pmax or qt < qmax:
            raise ValueError(
                f"target shape {shape} smaller than batch maxima "
                f"({P}, {N}, {pmax}, {qmax})")
        P, N, pmax, qmax = Pt, Nt, pt, qt
    M = np.zeros((P, N, pmax))
    F = np.zeros((P, N, qmax))
    phi = np.ones((P, qmax))
    r = np.zeros((P, N))
    nvec = np.ones((P, N))
    valid = np.zeros((P, N))
    pvalid = np.zeros((P, pmax))
    for k, pr in enumerate(problems):
        n, pp = pr.M.shape
        q = pr.F.shape[1]
        M[k, :n, :pp] = pr.M
        F[k, :n, :q] = pr.F
        phi[k, :q] = pr.phi
        r[k, :n] = pr.r
        nvec[k, :n] = pr.nvec
        valid[k, :n] = 1.0
        pvalid[k, :pp] = 1.0
    return {"M": M, "F": F, "phi": phi, "r": r, "nvec": nvec,
            "valid": valid, "pvalid": pvalid}


def _assemble_normal(M, F, phi, r, nvec, valid, pvalid):
    """Masked, column-scaled JOINT (params + bases) normal system —
    the one assembly shared by ``_solve_one`` below and the posterior
    slot kernel (``pint_tpu.sampling.serve_kernel`` builds its
    marginal precision by Schur-complementing the basis block of
    exactly this system), so a masking/scaling/pinning fix here
    reaches both consumers. Returns (Sigma, b, w, colmax, norm) with
    padded parameter columns pinned to identity so Cholesky stays
    PD."""
    p = M.shape[1]
    w = valid / nvec
    M = M * pvalid[None, :]
    colmax = jnp.max(jnp.abs(M), axis=0)
    colmax = jnp.where(colmax == 0, 1.0, colmax)
    Ms = M / colmax[None, :]
    norm = jnp.sqrt(jnp.sum(Ms * Ms * w[:, None], axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    big = jnp.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi])
    Sigma = Sigma + jnp.diag(prior)
    colvalid = jnp.concatenate([pvalid, jnp.ones(F.shape[1])])
    Sigma = Sigma * jnp.outer(colvalid, colvalid) + \
        jnp.diag(1.0 - colvalid)
    b = bigw.T @ r * colvalid
    return Sigma, b, w, colmax, norm


def _solve_one(M, F, phi, r, nvec, valid, pvalid):
    """Masked, preconditioned basis-Woodbury solve for one pulsar
    (same algebra as pint_tpu.gls._gls_kernel with padding guards).

    Returns (dparams, cov, chi2, chi2r): ``chi2`` is the linearized
    post-fit chi2 (parameters AND bases marginalized); ``chi2r`` is
    the chi2 of the residuals at the CURRENT point with only the
    noise bases marginalized — the quantity Residuals.chi2 reports
    (r^T C^-1 r), which the serve layer's residual requests return."""
    p = M.shape[1]
    Sigma, b, w, colmax, norm = _assemble_normal(
        M, F, phi, r, nvec, valid, pvalid)
    d = jnp.sqrt(jnp.diagonal(Sigma))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    cf = jax.scipy.linalg.cho_factor(Sigma / jnp.outer(d, d), lower=True)
    xhat = jax.scipy.linalg.cho_solve(cf, b / d) / d
    inv = jax.scipy.linalg.cho_solve(
        cf, jnp.eye(Sigma.shape[0])) / jnp.outer(d, d)
    rCr = jnp.sum(r * r * w)
    chi2 = rCr - xhat @ b
    # bases-only marginalization (see _gls_core's chi2): whiten by the
    # noise block alone so chi2r is r^T C^-1 r at the current point.
    # On an all-padded batch slot (q columns with unit prior, zero
    # data) the basis block is the identity and chi2r collapses to 0.
    q = F.shape[1]
    if q:
        bF = b[p:]
        SF = Sigma[p:, p:]
        dF = d[p:]
        cfF = jax.scipy.linalg.cho_factor(SF / jnp.outer(dF, dF),
                                          lower=True)
        chi2r = rCr - bF @ (jax.scipy.linalg.cho_solve(
            cfF, bF / dF) / dF)
    else:
        chi2r = rCr
    dparams = -xhat[:p] / colmax / norm * pvalid
    cov = inv[:p, :p] / jnp.outer(colmax, colmax) / jnp.outer(norm, norm)
    return dparams, cov, chi2, chi2r


def _pta_batch(M, F, phi, r, nvec, valid, pvalid):
    """Leading-axis batch of ``_solve_one`` — compiled through
    ``pta.shard.compile_with_plan`` (plain jit on one device;
    shard_map per-device blocks over the mesh's pulsar axis)."""
    return jax.vmap(_solve_one)(M, F, phi, r, nvec, valid, pvalid)


# ranks of the batch kernel's inputs/outputs (for the sharding plan)
_PTA_NDIMS_IN = (3, 3, 2, 2, 2, 2, 2)
_PTA_NDIMS_OUT = (2, 3, 1, 1)

# single-device compatibility alias (pre-ISSUE-17 name); the solve
# path now compiles through the plan cache
_pta_kernel = jax.jit(_pta_batch)


def _solve_one_np(M, F, phi, r, nvec, valid, pvalid):
    """Pure-numpy mirror of ``_solve_one`` (identical masked algebra,
    scipy Cholesky) — the host-failover path the dispatch supervisor
    takes for one padded batch slot when the device is timed out,
    broken or breaker-open."""
    from scipy.linalg import cho_factor, cho_solve

    p = M.shape[1]
    w = valid / nvec
    M = M * pvalid[None, :]
    colmax = np.max(np.abs(M), axis=0)
    colmax = np.where(colmax == 0, 1.0, colmax)
    Ms = M / colmax[None, :]
    norm = np.sqrt(np.sum(Ms * Ms * w[:, None], axis=0))
    norm = np.where(norm == 0, 1.0, norm)
    Mn = Ms / norm[None, :]
    big = np.concatenate([Mn, F], axis=1)
    bigw = big * w[:, None]
    Sigma = big.T @ bigw
    prior = np.concatenate([np.zeros(p), 1.0 / phi])
    Sigma = Sigma + np.diag(prior)
    colvalid = np.concatenate([pvalid, np.ones(F.shape[1])])
    Sigma = Sigma * np.outer(colvalid, colvalid) + \
        np.diag(1.0 - colvalid)
    b = bigw.T @ r * colvalid
    d = np.sqrt(np.diagonal(Sigma)).copy()
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    cf = cho_factor(Sigma / np.outer(d, d), lower=True)
    xhat = cho_solve(cf, b / d) / d
    inv = cho_solve(cf, np.eye(Sigma.shape[0])) / np.outer(d, d)
    rCr = float(np.sum(r * r * w))
    chi2 = rCr - xhat @ b
    q = F.shape[1]
    if q:
        bF = b[p:]
        SF = Sigma[p:, p:]
        dF = d[p:]
        cfF = cho_factor(SF / np.outer(dF, dF), lower=True)
        chi2r = rCr - bF @ (cho_solve(cfF, bF / dF) / dF)
    else:
        chi2r = rCr
    dparams = -xhat[:p] / colmax / norm * pvalid
    cov = inv[:p, :p] / np.outer(colmax, colmax) / np.outer(norm, norm)
    return dparams, cov, float(chi2), float(chi2r)


def pta_solve_np(stacked: dict):
    """Host-path batch solve: ``_solve_one_np`` per slot, stacked —
    the failover target for ``pta_solve`` and the serve engine's
    batched GLS dispatch."""
    P = stacked["M"].shape[0]
    outs = [_solve_one_np(stacked["M"][k], stacked["F"][k],
                          stacked["phi"][k], stacked["r"][k],
                          stacked["nvec"][k], stacked["valid"][k],
                          stacked["pvalid"][k])
            for k in range(P)]
    return (np.stack([o[0] for o in outs]),
            np.stack([o[1] for o in outs]),
            np.asarray([o[2] for o in outs]),
            np.asarray([o[3] for o in outs]))


def pta_solve(stacked: dict, mesh=None, axis: str = "pulsar"):
    """Solve the whole batch in one supervised device call (runtime
    watchdog + host ``pta_solve_np`` failover). With ``mesh``, the
    batch kernel is compiled through the ISSUE-17 sharding plan
    (``pta.shard.compile_with_plan``): shard_map per-device pulsar
    blocks with explicit in/out shardings — not GSPMD partitioning,
    which serialized the batched Cholesky sequence and LOST to
    single-device — padding P up to a mesh multiple. ``pvalid`` is
    donated to its alias-exact ``dparams`` output on real
    accelerators (the serve cache's donation discipline: never on
    the CPU backend, rebuilt fresh per dispatch)."""
    from pint_tpu import config
    from pint_tpu.runtime import get_supervisor

    P = np.asarray(stacked["M"]).shape[0]
    donate = (6,) if config.donation_enabled() and \
        jax.default_backend() != "cpu" else ()

    def run():
        """Place + dispatch + host read, all on the supervisor's
        guarded worker so the deadline covers completion. The shard
        plan is resolved here (lazily — ``pint_tpu.pta`` imports
        this module) and cached per (mesh, donation)."""
        from pint_tpu.pta.shard import batch_sharding, \
            compile_with_plan, pad_batch

        kernel = compile_with_plan(
            _pta_batch, name="pta.batch_solve",
            ndims_in=_PTA_NDIMS_IN, ndims_out=_PTA_NDIMS_OUT,
            mesh=mesh, axis=axis, donate_argnums=donate)
        arrs = pad_batch(stacked, mesh, axis)
        if mesh is not None:
            st = {k: jax.device_put(
                v, batch_sharding(mesh, axis,
                                  np.asarray(v).ndim))
                for k, v in arrs.items()}
        else:
            st = {k: jnp.asarray(v) for k, v in arrs.items()}
        out = kernel(st["M"], st["F"], st["phi"], st["r"], st["nvec"], st["valid"], st["pvalid"])  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
        return tuple(np.asarray(o)[:P] for o in out)

    from pint_tpu import obs

    with obs.span("pta.solve", npulsars=P,
                  sharded=mesh is not None):
        return get_supervisor().dispatch(
            run, key="pta.batch",
            fallback=lambda: pta_solve_np(stacked))


def fit_pta(pairs: Sequence[Tuple], maxiter: int = 2, mesh=None,
            track_mode=None) -> List[dict]:
    """Batch-fit [(toas, model), ...]: each iteration assembles every
    pulsar's linearized problem on the host (heterogeneous models), then
    solves ALL of them in one vmapped device call and applies the
    updates. Returns a PTAFitResult (a list of per-pulsar
    {chi2, errors}; models updated in place) whose ``.stats`` attribute
    is the SURVEY §5 scoreboard: total TOAs, wall time, TOAs/sec,
    device solve time. ``fit_pta.last_stats`` mirrors it for
    convenience (last call wins — not safe across interleaved fits)."""
    import time as _time

    t_start = _time.perf_counter()
    solve_s = 0.0
    out: List[dict] = [dict() for _ in pairs]
    for _ in range(max(1, maxiter)):
        problems = [build_problem(t, m, track_mode=track_mode)
                    for t, m in pairs]
        stacked = stack_problems(problems)
        t0 = _time.perf_counter()
        dparams, cov, chi2, _ = pta_solve(stacked, mesh=mesh)
        solve_s += _time.perf_counter() - t0
        for k, pr in enumerate(problems):
            names = pr.names
            x = dparams[k][:len(names)]
            for name, dx in zip(names, x):
                if name == "Offset":
                    continue
                pr.model.get_param(name).add_delta(float(dx))
            pr.model.invalidate_cache(params_only=True)
    # final pass: uncertainties + chi2 at the fitted point
    problems = [build_problem(t, m, track_mode=track_mode)
                for t, m in pairs]
    stacked = stack_problems(problems)
    t0 = _time.perf_counter()
    dparams, cov, chi2, _ = pta_solve(stacked, mesh=mesh)
    solve_s += _time.perf_counter() - t0
    for k, pr in enumerate(problems):
        errs = {}
        sig = np.sqrt(np.diag(cov[k]))
        for j, name in enumerate(pr.names):
            if name == "Offset":
                continue
            pr.model.get_param(name).uncertainty = float(sig[j])
            errs[name] = float(sig[j])
        out[k] = {"chi2": float(chi2[k]), "errors": errs}
    wall = _time.perf_counter() - t_start
    ntoa_total = sum(t.ntoas for t, _ in pairs)
    result = PTAFitResult(out)
    result.stats = {
        "npulsars": len(pairs), "ntoa_total": ntoa_total,
        "iterations": max(1, maxiter) + 1, "wall_time_s": wall,
        "device_solve_s": solve_s,
        "toas_per_sec": ntoa_total * (max(1, maxiter) + 1) / wall
        if wall else 0.0}
    fit_pta.last_stats = result.stats
    return result
