"""Parallel / multi-chip execution layer.

The reference is single-node (SURVEY.md §2c); its only parallelism is a
process pool for chi2 grids. Here the parallel axes are TPU-native:

- the TOA axis is block-sharded across the device mesh (the
  "sequence-parallel" axis: design-matrix rows, residuals, and noise
  bases live distributed; normal-equation assembly reduces over ICI) —
  `pint_tpu.parallel.fit_step`;
- the pulsar axis is an embarrassingly-parallel batch axis for PTA-scale
  runs (vmapped GLS across pulsars, sharded over the mesh) —
  `pint_tpu.parallel.pta`.
"""

from pint_tpu.parallel.fit_step import (  # noqa: F401
    build_fit_loop,
    build_fit_parts,
    build_fit_step,
    build_sharded_fit_step,
)
from pint_tpu.parallel.streaming import StreamingGLS  # noqa: F401
from pint_tpu.parallel.pta import (  # noqa: F401
    build_problem,
    fit_pta,
    pta_solve,
    stack_problems,
)
