"""Matrix-free GLS: streaming normal-equation accumulation +
preconditioned CG (ISSUE 12 tentpole).

Dense-Cholesky GLS materializes the (N, p+q) whitened design on
device, so it tops out where device memory does (the 131k sharded
oracle was the ceiling). PAPERS.md 1407.6710 formalizes the structure
that makes million-TOA fits cheap: the noise covariance is
N (diagonal, plus the rank-1-per-epoch ECORR blocks) plus a rank-q
basis term, so the whitened normal equations

    Sigma = [M|F]^T N_eff^-1 [M|F] + diag(0, 1/phi),
    b     = [M|F]^T N_eff^-1 r

never need the (N, p+q) matrices at full N: they are ACCUMULATED
chunk-by-chunk over the TOA stream (the GP formulation of PAPERS.md
1407.1838 — the same basis-Woodbury split the serve slot kernel
exploits). Peak device memory is O(chunk + (p+q)^2), unbounded in N.

Two device kernels, both supervised dispatches under obs spans:

- the **chunk accumulator**: ``build_fit_parts``'s assembly function
  (phase, Jacobian, bases — the SAME trace the dense step uses)
  evaluated on one fixed-size chunk, its Gram/cross/moment
  contributions folded into a small running state. Chunk sizes are
  quantized to powers of two (``config.stream_chunk`` — the whole-fit
  K discipline: the chunk length is a compile key, so a raw
  ceil(N/k) would compile one executable per N while the quantized
  set is bounded). ECORR rides the Sherman-Morrison segment path with
  a BOUNDARY CARRY: epochs are contiguous in the (epoch-sorted) TOA
  stream, so a chunk boundary splits at most one epoch, whose partial
  (s, E, wr) sums carry to the next chunk; complete epochs are
  downdated in-kernel. The weighted-mean subtraction of the reference
  residuals is applied POST-HOC from accumulated scalars (exact
  algebra — see ``_finalize_prep``), because a chunk cannot know the
  global mean.

- the **preconditioned-CG finalize**: the parameter-block solution of
  the accumulated system via its Schur complement
  ``S = A - B^T C^-1 B`` applied MATRIX-FREE (the basis-Woodbury
  inner solve ``C^-1`` is a q x q Cholesky; S itself is never
  formed), Jacobi-preconditioned from the accumulated diagonal, as a
  ``lax.while_loop`` with a RUNTIME iteration budget. The covariance
  rides the same loop: CG over the stacked right-hand sides
  ``[b_schur | I_p]`` solves xhat and S^-1 together (S is p x p, so
  exact-arithmetic CG terminates in <= p iterations; the budget is a
  safety bound, not a truncation).

Scale-safety: accumulated M-block quantities are stored relative to a
RUNNING column max (``cm``) — the streaming analog of the dense
kernel's two-stage column scaling, rescaled in-kernel when a chunk
raises the max — so no intermediate ever exceeds the exponent range
of TPU-emulated f64.

Numpy mirrors (``stream_solve_np``) implement the identical algebra
for the supervisor's host failover and the CPU equality oracles
(tests/test_streaming_gls.py: chunk-size invariance, CG-vs-dense
Cholesky across the component zoo).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.parallel.fit_step import _symm_mm, build_fit_parts

__all__ = ["StreamingGLS", "stream_solve_np", "acc_init_np",
           "acc_update_np", "acc_finalize_np", "cg_solve_np"]


# ------------------------------------------------------------ algebra
#
# Accumulator state (P = p + q; ~(P^2 + 4P + 16) * 8 bytes — small
# enough that StreamingGLS round-trips it to HOST between chunk
# dispatches: the supervisor's watchdog contract wants the D2H read
# inside the guarded closure, and a fresh upload per dispatch is what
# makes retries/failover donation-safe. On-device chunk chaining — a
# scan over resident chunk data, the PR-9 chain pattern — is the
# queued on-chip follow-up, see ROADMAP item 2):
#   cm    (p,)    running column max of |M| (power-free, exact max)
#   Sig   (P,P)   [M/cm | F]^T W [M/cm | F], ECORR-downdated for
#                 every COMPLETE epoch seen so far
#   b     (P,)    [M/cm | F]^T W r0, same downdates
#   u     (P,)    [M/cm | F]^T w·tmask      (mean-correction vector)
#   vE    (P,)    sum_k g_k s_k E_k         (mean x ECORR cross term)
#   scal  (8,)    [rCr0, swr0, sw, e_rr, e_swr, e_ss, carry_s,
#                  carry_wr]
#   carE  (P,)    partial E row of the boundary epoch
#   cjv   ()      boundary epoch's jitter variance
#   cid   ()      boundary epoch's global id (int32; -1 = none)


def _rescale_state(cm, Sig, b, u, vE, carE, cm_new, p):
    """Re-express every M-block-scaled accumulated quantity relative
    to a grown column max (algebraically exact: pure rescaling)."""
    rho = cm / cm_new
    rfull = jnp.concatenate([rho, jnp.ones(Sig.shape[0] - p,
                                           rho.dtype)])
    Sig = Sig * jnp.outer(rfull, rfull)
    return Sig, b * rfull, u * rfull, vE * rfull, carE * rfull


def _acc_chunk(state, M, Fv, r0, nvec, valid, eid, jv_toa, tmask,
               f32mm: bool, has_ecorr: bool,
               health: bool = False):
    """Fold one chunk's contributions into the accumulator state.
    Pure jittable; shapes fixed by the chunk length. ``jv_toa`` is
    the per-TOA jitter variance (jvar[eid] gathered on host).

    With ``health`` (a STATIC flag, ISSUE 14) the chunk additionally
    returns a 2-vector ``[nonfinite_count, rescale_magnitude]`` —
    non-finites across the accumulated (Sig, b) plus the chunk's
    residual/design rows, and the worst running-colmax growth factor
    this chunk caused (a huge late-stream rescale is the scale-safety
    machinery working overtime — worth seeing before it overflows
    TPU-emulated f64's f32-limited exponent range). Compiled out
    entirely when disarmed."""
    cm, Sig, b, u, vE, scal, carE, cjv, cid = state
    p = cm.shape[0]
    P = Sig.shape[0]
    C = M.shape[0]
    w = valid / nvec
    # running column max: grow-only, then rescale history
    cm_c = jnp.max(jnp.abs(M) * valid[:, None].astype(M.dtype),
                   axis=0).astype(jnp.float64)
    cm_new = jnp.maximum(cm, jnp.where(cm_c == 0, cm, cm_c))
    cm_new = jnp.where(cm_new == 0, 1.0, cm_new)
    if health:
        # worst colmax growth this chunk forced (cm is grow-only and
        # >= 1 after init, so the ratio is well-defined)
        resc = jnp.max(cm_new / jnp.where(cm == 0, 1.0, cm))
    Sig, b, u, vE, carE = _rescale_state(cm, Sig, b, u, vE, carE,
                                         cm_new, p)
    cm = cm_new

    def _out(st):
        if not health:
            return st
        nf = (jnp.sum(~jnp.isfinite(st[1]))
              + jnp.sum(~jnp.isfinite(st[2]))
              + jnp.sum(~jnp.isfinite(M))
              + jnp.sum(~jnp.isfinite(r0))).astype(jnp.float64)
        return st, jnp.stack([nf, resc])
    Ms = M / cm[None, :].astype(M.dtype)
    big = jnp.concatenate([Ms, Fv.astype(Ms.dtype)], axis=1)
    sw = jnp.sqrt(w)
    bigs = big * sw[:, None].astype(big.dtype)
    Sig = Sig + _symm_mm(bigs, bigs, f32mm)
    bigw64 = big.astype(jnp.float64) * w[:, None]
    b = b + bigw64.T @ r0
    u = u + bigw64.T @ tmask
    wt = w * tmask
    scal = scal.at[0].add(jnp.sum(w * r0 * r0))
    scal = scal.at[1].add(jnp.sum(wt * r0))
    scal = scal.at[2].add(jnp.sum(wt))
    if not has_ecorr:
        return _out((cm, Sig, b, u, vE, scal, carE, cjv, cid))

    # ---- ECORR Sherman-Morrison with boundary carry ----------------
    # chunk-local segment relabel (requires eid nondecreasing within
    # the epoch-sorted stream; StreamingGLS sorts at build)
    rid = eid - eid[0]
    seg = partial(jax.ops.segment_sum, segment_ids=rid,
                  num_segments=C)
    s_seg = seg(w)
    E_seg = seg(bigw64)
    wr_seg = seg(w * r0)
    jv_seg = jax.ops.segment_max(jv_toa, rid, num_segments=C)
    jv_seg = jnp.where(jnp.isfinite(jv_seg), jv_seg, 0.0)
    # merge the carried boundary epoch into segment 0 when it is the
    # same global epoch; otherwise the carry is COMPLETE — downdate it
    merge = (eid[0] == cid) & (cid >= 0)
    c_s, c_wr = scal[6], scal[7]
    g_c = jnp.where(merge, 0.0, cjv / (1.0 + cjv * c_s))
    Sig = Sig - g_c * jnp.outer(carE, carE)
    b = b - g_c * c_wr * carE
    vE = vE + g_c * c_s * carE
    scal = scal.at[3].add(g_c * c_wr * c_wr)
    scal = scal.at[4].add(g_c * c_s * c_wr)
    scal = scal.at[5].add(g_c * c_s * c_s)
    s_seg = s_seg.at[0].add(jnp.where(merge, c_s, 0.0))
    wr_seg = wr_seg.at[0].add(jnp.where(merge, c_wr, 0.0))
    E_seg = E_seg.at[0].add(jnp.where(merge, 1.0, 0.0) * carE)
    jv_seg = jv_seg.at[0].max(jnp.where(merge, cjv, 0.0))
    # complete segments: 0..L-1 (L = the chunk's last epoch, carried)
    L = rid[C - 1]
    mask = (jnp.arange(C) < L).astype(jnp.float64)
    g = jv_seg / (1.0 + jv_seg * s_seg) * mask
    sg = jnp.sqrt(g)
    Eg = E_seg * sg[:, None]
    Sig = Sig - _symm_mm(Eg.astype(bigs.dtype),
                         Eg.astype(bigs.dtype), f32mm)
    b = b - Eg.T @ (sg * wr_seg)
    vE = vE + Eg.T @ (sg * s_seg)
    scal = scal.at[3].add(jnp.sum(g * wr_seg * wr_seg))
    scal = scal.at[4].add(jnp.sum(g * s_seg * wr_seg))
    scal = scal.at[5].add(jnp.sum(g * s_seg * s_seg))
    # new carry: the chunk's trailing (possibly straddling) epoch
    scal = scal.at[6].set(s_seg[L])
    scal = scal.at[7].set(wr_seg[L])
    carE = E_seg[L]
    cjv = jv_seg[L]
    cid = eid[C - 1]
    return _out((cm, Sig, b, u, vE, scal, carE, cjv, cid))


def _flush_carry(state):
    """Downdate the final boundary epoch (end of stream)."""
    cm, Sig, b, u, vE, scal, carE, cjv, cid = state
    c_s, c_wr = scal[6], scal[7]
    g_c = jnp.where(cid >= 0, cjv / (1.0 + cjv * c_s), 0.0)
    Sig = Sig - g_c * jnp.outer(carE, carE)
    b = b - g_c * c_wr * carE
    vE = vE + g_c * c_s * carE
    scal = scal.at[3].add(g_c * c_wr * c_wr)
    scal = scal.at[4].add(g_c * c_s * c_wr)
    scal = scal.at[5].add(g_c * c_s * c_s)
    scal = scal.at[6].set(0.0)
    scal = scal.at[7].set(0.0)
    return (cm, Sig, b, u, vE, scal, jnp.zeros_like(carE),
            jnp.zeros_like(cjv), jnp.full_like(cid, -1))


def _finalize_prep(state, phi, incoffset: bool):
    """Mean-correct and prior-load the accumulated system: returns
    (Sigma, b, rCr, cm) of the EXACT dense normal equations (modulo
    rounding) the one-shot kernel would have assembled."""
    cm, Sig, b, u, vE, scal, _, _, _ = state
    p = cm.shape[0]
    rCr0, swr0, sw = scal[0], scal[1], scal[2]
    e_rr, e_swr, e_ss = scal[3], scal[4], scal[5]
    mu = jnp.where(incoffset & (sw > 0), swr0 / jnp.where(sw > 0, sw,
                                                          1.0), 0.0)
    # the mean correction r -> r0 - mu: b loses mu*(u - vE) (vE is
    # the ECORR downdate's response to the constant direction)
    b = b - mu * (u - vE)
    rCr = (rCr0 - 2.0 * mu * swr0 + mu * mu * sw) \
        - (e_rr - 2.0 * mu * e_swr + mu * mu * e_ss)
    q = Sig.shape[0] - p
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi]) if q else \
        jnp.zeros(p)
    return Sig + jnp.diag(prior), b, rCr, cm


def _cg_schur(Sigma, b, rCr, cm, budget, tol):
    """Matrix-free preconditioned-CG solve of the parameter block of
    ``Sigma x = b`` via the Schur complement of the basis block.

    The whitened normal equations are Jacobi-scaled to unit diagonal
    (the preconditioner the accumulated diagonal provides), the basis
    block C is Cholesky-factored ONCE (the basis-Woodbury inner
    solve, q x q), and the Schur operator
    ``v -> A v - B^T (C^-1 (B v))`` is applied matrix-free inside a
    ``lax.while_loop`` CG over the stacked RHS ``[b_schur | I_p]`` —
    solution and covariance in one loop. ``budget`` is a RUNTIME
    iteration bound (compile-free across callers); ``tol`` the
    relative residual target. Returns (dparams, cov, chi2, chi2r,
    xf, ok, iters, rel_resid): dparams is the correction to ADD (the
    _gls_core sign convention), ok False when the basis Cholesky or
    CG failed (caller falls back to a dense/host solve), and
    ``rel_resid`` the worst final relative CG residual across the
    stacked RHS — solver effort that used to be computed on device
    and thrown away (ISSUE 14: it now rides every solve as an extra
    scalar of the SAME dispatch, feeding the ``HealthMonitor``, the
    ``StreamingGLSFitter`` result surface and the scan artifact)."""
    P = Sigma.shape[0]
    p = cm.shape[0]
    q = P - p
    d = jnp.sqrt(jnp.diagonal(Sigma))
    d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
    St = Sigma / jnp.outer(d, d)
    bt = b / d
    A = St[:p, :p]
    if q:
        B = St[p:, :p]
        Cq = St[p:, p:]
        cf = jax.scipy.linalg.cho_factor(Cq, lower=True)
        CiB = jax.scipy.linalg.cho_solve(cf, B)          # (q, p)
        bF = bt[p:]
        CibF = jax.scipy.linalg.cho_solve(cf, bF)
        rhs0 = bt[:p] - B.T @ CibF
        chi2r = rCr - bF @ CibF
        # exact Schur diagonal — the Jacobi preconditioner of the
        # REDUCED system (diag(A) is 1 after scaling; the correction
        # is the basis-projection mass per column)
        dS = 1.0 - jnp.sum(B * CiB, axis=0)
    else:
        B = jnp.zeros((0, p))
        CiB = jnp.zeros((0, p))
        rhs0 = bt[:p]
        chi2r = rCr
        dS = jnp.ones(p)
    dS = jnp.where(dS > 1e-14, dS, 1.0)

    def op(V):
        out = A @ V
        if q:
            out = out - CiB.T @ (B @ V)
        return out

    RHS = jnp.concatenate([rhs0[:, None], jnp.eye(p)], axis=1)
    bnorm = jnp.sqrt(jnp.sum(RHS * RHS, axis=0))
    bnorm = jnp.where(bnorm == 0, 1.0, bnorm)
    X0 = jnp.zeros_like(RHS)
    R0 = RHS
    Z0 = R0 / dS[:, None]
    rz0 = jnp.sum(R0 * Z0, axis=0)

    def active(R):
        return jnp.sqrt(jnp.sum(R * R, axis=0)) > tol * bnorm

    def cond(c):
        k, X, R, Z, Pd, rz = c
        return (k < budget) & jnp.any(active(R))

    def body(c):
        k, X, R, Z, Pd, rz = c
        act = active(R)
        AP = op(Pd)
        pAp = jnp.sum(Pd * AP, axis=0)
        alpha = jnp.where(act & (pAp > 0),
                          rz / jnp.where(pAp > 0, pAp, 1.0), 0.0)
        X = X + alpha[None, :] * Pd
        R = R - alpha[None, :] * AP
        Zn = R / dS[:, None]
        rzn = jnp.sum(R * Zn, axis=0)
        beta = jnp.where(act & (rz > 0),
                         rzn / jnp.where(rz > 0, rz, 1.0), 0.0)
        Pd = Zn + beta[None, :] * Pd
        return (k + 1, X, R, Z, Pd, rzn)

    k, X, R, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), X0, R0, Z0, Z0, rz0))
    xt = X[:, 0]
    Sinv = X[:, 1:]
    # basis amplitudes + full-system products for chi2
    if q:
        yt = jax.scipy.linalg.cho_solve(cf, bF - B @ xt)
        chi2 = rCr - (xt @ bt[:p] + yt @ bF)
        xf = yt / d[p:]
    else:
        chi2 = rCr - xt @ bt[:p]
        xf = jnp.zeros(0)
    scale = d[:p] * cm
    dparams = -xt / scale
    cov = Sinv / jnp.outer(scale, scale)
    resid = jnp.max(jnp.sqrt(jnp.sum(R * R, axis=0)) / bnorm)
    ok = jnp.all(jnp.isfinite(xt)) & jnp.all(jnp.isfinite(cov)) \
        & jnp.isfinite(chi2) & (resid <= jnp.sqrt(tol))
    return dparams, cov, chi2, chi2r, xf, ok, k, resid


# -------------------------------------------------- jitted wrappers


def _finalize_kernel(state, phi, sfull, budget, tol,
                     incoffset: bool = True):
    """Flush the ECORR carry, mean-correct, and CG-solve. ``sfull``
    is the jac32 column-unscale vector (ones when jac32 off)."""
    state = _flush_carry(state)
    Sigma, b, rCr, cm = _finalize_prep(state, phi, incoffset)
    dparams, cov, chi2, chi2r, xf, ok, iters, resid = _cg_schur(
        Sigma, b, rCr, cm, budget, tol)
    dparams = dparams * sfull
    cov = cov * jnp.outer(sfull, sfull)
    return dparams, cov, chi2, chi2r, xf, ok, iters, resid


# ------------------------------------------------------ numpy mirror


def acc_init_np(p: int, q: int):
    """Zero accumulator state (host mirror layout == device layout)."""
    P = p + q
    return [np.ones(p), np.zeros((P, P)), np.zeros(P), np.zeros(P),
            np.zeros(P), np.zeros(8), np.zeros(P), np.asarray(0.0),
            np.asarray(-1, np.int32)]


def acc_update_np(state, M, F, r0, nvec, valid, tmask=None,
                  eid=None, jv_toa=None):
    """Numpy mirror of ``_acc_chunk`` (f64 accumulation, same
    boundary-carry ECORR downdates) — the host-failover path and the
    chunk-invariance oracle. Mutates and returns ``state``."""
    cm, Sig, b, u, vE, scal, carE, cjv, cid = state
    p = cm.shape[0]
    M = np.asarray(M, np.float64)
    C = M.shape[0]
    if tmask is None:
        tmask = valid
    w = valid / nvec
    cm_c = np.max(np.abs(M) * valid[:, None], axis=0) \
        if C else np.zeros(p)
    cm_new = np.maximum(cm, np.where(cm_c == 0, cm, cm_c))
    cm_new[cm_new == 0] = 1.0
    rho = cm / cm_new
    rfull = np.concatenate([rho, np.ones(Sig.shape[0] - p)])
    Sig *= np.outer(rfull, rfull)
    b *= rfull
    u *= rfull
    vE *= rfull
    carE *= rfull
    cm = cm_new
    big = np.concatenate([M / cm[None, :], np.asarray(F, np.float64)],
                         axis=1)
    bigw = big * w[:, None]
    Sig += big.T @ bigw
    b += bigw.T @ r0
    u += bigw.T @ tmask
    wt = w * tmask
    scal[0] += float(np.sum(w * r0 * r0))
    scal[1] += float(np.sum(wt * r0))
    scal[2] += float(np.sum(wt))
    state[0], state[1], state[2], state[3], state[4] = \
        cm, Sig, b, u, vE
    if eid is None or jv_toa is None:
        return state
    # ECORR boundary-carry (mirror of the in-kernel path)
    eid = np.asarray(eid)
    order_ok = np.all(np.diff(eid) >= 0)
    if not order_ok:
        raise ValueError("streaming ECORR requires epoch-sorted rows")
    uniq, starts = np.unique(eid, return_index=True)
    ends = np.append(starts[1:], C)
    for k0, (gidx, s0, s1) in enumerate(zip(uniq, starts, ends)):
        seg_w = w[s0:s1]
        s_s = float(np.sum(seg_w))
        E_s = bigw[s0:s1].T @ np.ones(s1 - s0)
        wr_s = float(np.sum(seg_w * r0[s0:s1]))
        jv_s = float(np.max(jv_toa[s0:s1])) if s1 > s0 else 0.0
        if k0 == 0 and gidx == int(cid) and int(cid) >= 0:
            s_s += scal[6]
            wr_s += scal[7]
            E_s = E_s + carE
            jv_s = max(jv_s, float(cjv))
        elif k0 == 0 and int(cid) >= 0:
            _downdate_np(state, float(cjv))
            cid = np.asarray(-1, np.int32)
        if gidx == uniq[-1]:
            scal[6], scal[7] = s_s, wr_s
            state[6] = E_s
            state[7] = np.asarray(jv_s)
            state[8] = np.asarray(gidx, np.int32)
        else:
            g = jv_s / (1.0 + jv_s * s_s)
            state[1] -= g * np.outer(E_s, E_s)
            state[2] -= g * wr_s * E_s
            state[4] += g * s_s * E_s
            scal[3] += g * wr_s * wr_s
            scal[4] += g * s_s * wr_s
            scal[5] += g * s_s * s_s
    return state


def _downdate_np(state, jv):
    """Downdate the carried boundary epoch in the host mirror."""
    scal = state[5]
    c_s, c_wr = scal[6], scal[7]
    carE = state[6]
    g = jv / (1.0 + jv * c_s)
    state[1] -= g * np.outer(carE, carE)
    state[2] -= g * c_wr * carE
    state[4] += g * c_s * carE
    scal[3] += g * c_wr * c_wr
    scal[4] += g * c_s * c_wr
    scal[5] += g * c_s * c_s
    scal[6] = 0.0
    scal[7] = 0.0
    state[6] = np.zeros_like(carE)
    state[7] = np.asarray(0.0)
    state[8] = np.asarray(-1, np.int32)


def cg_solve_np(Sigma, b, rCr, cm, budget=None, tol=1e-13):
    """Numpy mirror of ``_cg_schur`` (same Jacobi scaling, Schur
    operator, preconditioned CG over stacked RHS)."""
    from scipy.linalg import cho_factor, cho_solve

    P = Sigma.shape[0]
    p = cm.shape[0]
    q = P - p
    d = np.sqrt(np.diagonal(Sigma)).copy()
    d[(d == 0) | ~np.isfinite(d)] = 1.0
    St = Sigma / np.outer(d, d)
    bt = b / d
    A = St[:p, :p]
    if q:
        B = St[p:, :p]
        cf = cho_factor(St[p:, p:], lower=True)
        CiB = cho_solve(cf, B)
        bF = bt[p:]
        CibF = cho_solve(cf, bF)
        rhs0 = bt[:p] - B.T @ CibF
        chi2r = rCr - bF @ CibF
        dS = 1.0 - np.sum(B * CiB, axis=0)
    else:
        B = np.zeros((0, p))
        CiB = np.zeros((0, p))
        rhs0 = bt[:p]
        chi2r = rCr
        dS = np.ones(p)
    dS = np.where(dS > 1e-14, dS, 1.0)
    if budget is None:
        budget = 8 * (p + 1)

    def op(V):
        out = A @ V
        if q:
            out = out - CiB.T @ (B @ V)
        return out

    RHS = np.concatenate([rhs0[:, None], np.eye(p)], axis=1)
    bnorm = np.sqrt(np.sum(RHS * RHS, axis=0))
    bnorm[bnorm == 0] = 1.0
    X = np.zeros_like(RHS)
    R = RHS.copy()
    Z = R / dS[:, None]
    rz = np.sum(R * Z, axis=0)
    Pd = Z.copy()
    iters = 0
    for _ in range(int(budget)):
        act = np.sqrt(np.sum(R * R, axis=0)) > tol * bnorm
        if not np.any(act):
            break
        iters += 1
        AP = op(Pd)
        pAp = np.sum(Pd * AP, axis=0)
        with np.errstate(divide="ignore", invalid="ignore"):
            alpha = np.where(act & (pAp > 0), rz / np.where(
                pAp > 0, pAp, 1.0), 0.0)
        X += alpha[None, :] * Pd
        R -= alpha[None, :] * AP
        Zn = R / dS[:, None]
        rzn = np.sum(R * Zn, axis=0)
        with np.errstate(divide="ignore", invalid="ignore"):
            beta = np.where(act & (rz > 0), rzn / np.where(
                rz > 0, rz, 1.0), 0.0)
        Pd = Zn + beta[None, :] * Pd
        rz = rzn
    xt = X[:, 0]
    Sinv = X[:, 1:]
    if q:
        yt = cho_solve(cf, bF - B @ xt)
        chi2 = rCr - (xt @ bt[:p] + yt @ bF)
        xf = yt / d[p:]
    else:
        chi2 = rCr - xt @ bt[:p]
        xf = np.zeros(0)
    scale = d[:p] * cm
    dparams = -xt / scale
    cov = Sinv / np.outer(scale, scale)
    resid = float(np.max(np.sqrt(np.sum(R * R, axis=0)) / bnorm))
    ok = bool(np.all(np.isfinite(xt)) and np.all(np.isfinite(cov))
              and np.isfinite(chi2) and resid <= np.sqrt(tol))
    return (dparams, cov, float(chi2), float(chi2r), xf, ok, iters,
            resid)


def acc_finalize_np(state, phi, sfull=None, incoffset=True,
                    budget=None, tol=1e-13):
    """Numpy mirror of ``_finalize_kernel``: flush carry,
    mean-correct, prior-load, CG-solve."""
    if int(state[8]) >= 0:
        _downdate_np(state, float(state[7]))
    cm, Sig, b, u, vE, scal = state[0], state[1], state[2], \
        state[3], state[4], state[5]
    p = cm.shape[0]
    rCr0, swr0, sw = scal[0], scal[1], scal[2]
    e_rr, e_swr, e_ss = scal[3], scal[4], scal[5]
    mu = (swr0 / sw) if (incoffset and sw > 0) else 0.0
    b = b - mu * (u - vE)
    rCr = (rCr0 - 2.0 * mu * swr0 + mu * mu * sw) \
        - (e_rr - 2.0 * mu * e_swr + mu * mu * e_ss)
    q = Sig.shape[0] - p
    prior = np.concatenate([np.zeros(p), 1.0 / np.asarray(phi)]) \
        if q else np.zeros(p)
    Sigma = Sig + np.diag(prior)
    out = cg_solve_np(Sigma, b, float(rCr), cm, budget=budget,
                      tol=tol)
    if sfull is not None:
        dp, cov = out[0] * sfull, out[1] * np.outer(sfull, sfull)
        out = (dp, cov) + out[2:]
    return out


def stream_solve_np(M, F, phi, r0, nvec, chunk: int,
                    incoffset: bool = True, eid=None, jvar=None,
                    tol=1e-13):
    """Host streaming solve over prebuilt dense rows (the failover
    and oracle path): chunked ``acc_update_np`` + ``acc_finalize_np``.
    ``r0`` must be the NOT-mean-subtracted residuals."""
    M = np.asarray(M, np.float64)
    n, p = M.shape
    F = np.asarray(F, np.float64)
    q = F.shape[1]
    state = acc_init_np(p, q)
    jv_toa = None if (eid is None or jvar is None) \
        else np.asarray(jvar)[np.asarray(eid)]
    for s0 in range(0, n, int(chunk)):
        s1 = min(n, s0 + int(chunk))
        sl = slice(s0, s1)
        acc_update_np(
            state, M[sl], F[sl], np.asarray(r0)[sl],
            np.asarray(nvec)[sl], np.ones(s1 - s0),
            eid=None if eid is None else np.asarray(eid)[sl],
            jv_toa=None if jv_toa is None else jv_toa[sl])
    return acc_finalize_np(state, phi, incoffset=incoffset, tol=tol)


# --------------------------------------------------------- StreamingGLS


class StreamingGLS:
    """One model+TOAs' streaming GLS machinery: the chunked
    accumulator and the CG finalize, built ONCE (one compile per
    quantized chunk length) and re-runnable at any parameter point
    (th, tl) — the unit ``pint_tpu.gls.StreamingGLSFitter`` iterates.

    Build-time host work: ``build_fit_parts`` (the same assembly the
    dense step compiles), an epoch-sort permutation when ECORR is
    active (accumulation is row-order-invariant, and epoch-contiguous
    rows are what lets a chunk boundary split at most one epoch), and
    per-chunk host views of every TOA-axis array. Device work per
    pass: ceil(N/C) SEQUENTIAL supervised chunk dispatches — device
    memory is O(C + (p+q)^2), and the ~40 kB state round-trips to
    host between dispatches (fresh uploads keep supervisor
    retries/failover donation-safe; the D2H read inside the guarded
    closure is the watchdog contract) — plus one finalize dispatch.
    Per-dispatch RTT over the axon tunnel makes a pass
    RTT * ceil(N/C)-bound there; the on-chip follow-up (ROADMAP
    item 2) is device-resident chunk chaining via the PR-9 scan
    pattern.
    """

    def __init__(self, model, toas, chunk: Optional[int] = None,
                 **flags):
        from pint_tpu import config

        if flags.get("wideband"):
            raise ValueError("streaming GLS does not support "
                             "wideband TOAs (stacked DM rows); use "
                             "the dense fitters")
        flags.pop("wideband", None)
        parts_fn, args, names, meta = build_fit_parts(model, toas,
                                                      **flags)
        self.names = names
        self.meta = meta
        self.model = model
        self.toas = toas
        n = toas.ntoas
        self.ntoa = n
        self.chunk = config.stream_chunk(n) if chunk is None \
            else int(chunk)
        (th, tl, fh, fl, batch, sc, F, phi, nvec, valid, eid,
         jvar) = args
        self.th0 = np.asarray(th, np.float64).copy()
        self.tl0 = np.asarray(tl, np.float64).copy()
        self.fh = np.asarray(fh)
        self.fl = np.asarray(fl)
        self.phi = np.asarray(phi)
        self.p = len(names)
        self.q = self.phi.shape[0]
        jvar_np = np.asarray(jvar)
        eid_np = np.asarray(eid)
        # epoch-sort permutation: accumulation is row-order-invariant
        # and the boundary-carry ECORR path needs nondecreasing eid
        if meta["has_ecorr"] and np.any(np.diff(eid_np) < 0):
            perm = np.argsort(eid_np, kind="stable")
        else:
            perm = None
        self._perm = perm

        def host(a):
            a = np.asarray(a)
            if perm is not None and a.ndim >= 1 and a.shape[0] == n:
                return a[perm]
            if perm is not None and a.ndim == 3 and a.shape[1] == n:
                return a[:, perm]
            return a

        self._batch = jax.tree.map(host, jax.tree.map(np.asarray,
                                                      batch))
        self._sc = jax.tree.map(host, jax.tree.map(np.asarray, sc))
        self._F = host(F)
        self._nvec = host(nvec)
        self._valid = host(valid)
        self._eid = host(eid_np)
        self._jv_toa = jvar_np[self._eid]
        self._jvar = jvar_np
        self.nchunks = -(-n // self.chunk)
        self.last_pass_hv = None   # worst chunk hv of the last pass
        incoffset = bool(meta["incoffset"])
        f32mm = bool(meta["f32mm"])
        has_ecorr = bool(meta["has_ecorr"])
        health_on = bool(meta["health"])
        self.health_on = health_on
        self.incoffset = incoffset

        def chunk_fn(state, th_, tl_, fh_, fl_, batch_c, sc_c, F_c,
                     phi_, nvec_c, valid_c, eid_c, jvar_, jv_c):
            # parameter VALUES — including frozen ones, phi and the
            # epoch jitter variances — are runtime arguments, never
            # trace constants (the G10 discipline)
            M, Fv, r0, nvec2, valid2, eid2, tmask = parts_fn(
                th_, tl_, fh_, fl_, batch_c, sc_c, F_c, phi_,
                nvec_c, valid_c, eid_c, jvar_)
            return _acc_chunk(state, M, Fv, r0, nvec2, valid2, eid2,
                              jv_c, tmask, f32mm=f32mm,
                              has_ecorr=has_ecorr, health=health_on)

        donate = config.donation_enabled() and \
            jax.default_backend() != "cpu"
        self._jit_chunk = jax.jit(chunk_fn, donate_argnums=(0,)) \
            if donate else jax.jit(chunk_fn)
        self._jit_final = jax.jit(partial(_finalize_kernel,
                                          incoffset=incoffset))

    # -- chunk views ---------------------------------------------------

    def _chunk_views(self, k: int):
        """Host views/pads of chunk k's per-TOA arrays (last chunk
        edge-padded with valid=0, the _pad_leaf convention)."""
        C = self.chunk
        n = self.ntoa
        s0, s1 = k * C, min(n, (k + 1) * C)
        pad = C - (s1 - s0)

        def cut(a):
            a = np.asarray(a)
            if a.ndim == 0 or a.shape == (1,):
                return a
            if a.ndim == 3 and a.shape[1] == n:
                v = a[:, s0:s1]
                return np.pad(v, [(0, 0), (0, pad), (0, 0)],
                              mode="edge") if pad else v
            if a.ndim >= 1 and a.shape[0] == n:
                v = a[s0:s1]
                if pad:
                    v = np.pad(v, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                               mode="edge")
                return v
            return a

        batch_c = jax.tree.map(cut, self._batch)
        sc_c = jax.tree.map(cut, self._sc)
        F_c = cut(self._F)
        nvec_c = cut(self._nvec)
        valid_c = cut(self._valid)
        if pad:
            valid_c = valid_c.copy()
            valid_c[-pad:] = 0.0
        eid_c = cut(self._eid)
        jv_c = cut(self._jv_toa)
        return batch_c, sc_c, F_c, nvec_c, valid_c, eid_c, jv_c

    def _init_state_np(self):
        return acc_init_np(self.p, self.q)

    @property
    def default_budget(self) -> int:
        """Runtime CG iteration budget when ``solve`` is given none
        — THE single source of the formula (the fitter's
        ``cg_budget`` surface, the scan artifact and the
        HealthMonitor's exhaustion threshold all derive from it, so
        they can never disagree with what the solver actually ran):
        exact-arithmetic CG terminates in <= p iterations, 8x is the
        rounding-safety margin."""
        return 8 * (self.p + 1)

    # -- device passes -------------------------------------------------

    def accumulate(self, th, tl, observe: bool = True):
        """One full streaming pass at parameter point (th, tl):
        ceil(N/C) supervised chunk dispatches. Returns the host-side
        accumulator state. Raises DispatchError through to the caller
        (the fitter's failover boundary).

        ``observe=False`` suppresses the health observation of this
        pass (the downhill fitter's line-search TRIAL passes: a
        rejected overshoot legitimately produces garbage — that is
        the damping working, not an incident; the fitter observes
        the entry pass and every ACCEPTED trial itself)."""
        from pint_tpu import obs
        from pint_tpu.runtime import get_supervisor

        sup = get_supervisor()
        state = tuple(np.asarray(x) for x in self._init_state_np())
        th = np.asarray(th, np.float64)
        tl = np.asarray(tl, np.float64)
        health_on = self.health_on
        hv_worst = None
        self.last_pass_hv = None   # set below when armed
        with obs.span("stream.accumulate", ntoa=self.ntoa,
                      chunk=self.chunk, nchunks=self.nchunks):
            for k in range(self.nchunks):
                (batch_c, sc_c, F_c, nvec_c, valid_c, eid_c,
                 jv_c) = self._chunk_views(k)

                def run(st=state, bc=batch_c, scc=sc_c, Fc=F_c,
                        nc=nvec_c, vc=valid_c, ec=eid_c, jc=jv_c):
                    # fresh device uploads per call (donation-safe
                    # under supervisor retries); host reads inside so
                    # the watchdog covers completion
                    dev = tuple(jnp.asarray(x) for x in st)
                    out = self._jit_chunk(dev, jnp.asarray(th), jnp.asarray(tl), jnp.asarray(self.fh), jnp.asarray(self.fl), jax.tree.map(jnp.asarray, bc), jax.tree.map(jnp.asarray, scc), jnp.asarray(Fc), jnp.asarray(self.phi), jnp.asarray(nc), jnp.asarray(vc), jnp.asarray(ec), jnp.asarray(self._jvar), jnp.asarray(jc))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                    if health_on:
                        st_out, hv = out
                        return (tuple(np.asarray(o) for o in st_out),
                                np.asarray(hv))
                    return tuple(np.asarray(o) for o in out)

                out = sup.dispatch(run, key="stream.chunk")
                if k == 0 and not getattr(self, "_perf_ledgered",
                                          False):
                    # ISSUE 15: enrich the chunk kernel's compile-
                    # ledger entry (the supervisor's first_call just
                    # recorded its wall) with XLA cost analysis —
                    # once per instance. defer_cost: the probe's
                    # lower().compile() re-pays the in-process
                    # compile, so it runs on a background thread,
                    # never inside the streaming pass. The roofline
                    # for the streaming chunk derives from this
                    # entry in bench's --scan artifact.
                    self._perf_ledgered = True
                    try:
                        from pint_tpu.obs import perf as _perf

                        init = tuple(jnp.asarray(x)
                                     for x in self._init_state_np())
                        _perf.note_compile(
                            "stream.chunk", kind="stream",
                            backend=jax.default_backend(),
                            jitted=self._jit_chunk,
                            args=(init, jnp.asarray(th),
                                  jnp.asarray(tl),
                                  jnp.asarray(self.fh),
                                  jnp.asarray(self.fl),
                                  jax.tree.map(jnp.asarray, batch_c),
                                  jax.tree.map(jnp.asarray, sc_c),
                                  jnp.asarray(F_c),
                                  jnp.asarray(self.phi),
                                  jnp.asarray(nvec_c),
                                  jnp.asarray(valid_c),
                                  jnp.asarray(eid_c),
                                  jnp.asarray(self._jvar),
                                  jnp.asarray(jv_c)),
                            defer_cost=True)
                    except Exception:
                        pass
                if health_on:
                    state, hv = out
                    # fold the pass's worst chunk vector (max over
                    # both slots) — ONE observe per pass, not per
                    # chunk, keeps the armed cost O(1) in nchunks
                    hv_worst = hv if hv_worst is None else \
                        np.maximum(hv_worst, hv)
                else:
                    state = out
            if hv_worst is not None:
                # kept for the caller either way: the downhill
                # fitter observes an ACCEPTED trial's pass vector
                # itself after suppressing the per-trial observation
                self.last_pass_hv = hv_worst
                if observe:
                    from pint_tpu.obs import health as _health

                    _health.observe(
                        "stream.chunk",
                        {"nonfinite": hv_worst[0],
                         "rescale": hv_worst[1]},
                        key="stream.chunk")
        from pint_tpu.obs import metrics as om

        om.counter("pint_tpu_stream_chunk_dispatches_total",
                   "streaming-GLS chunk dispatches").inc(self.nchunks)
        return state

    def solve(self, state, budget: Optional[int] = None,
              tol: float = 1e-13, observe: bool = True):
        """CG-finalize an accumulated state (one supervised
        dispatch). Returns (dparams, cov, chi2, chi2r, xf, ok,
        iters, rel_resid) — dparams the correction to ADD aligned
        with ``self.names``, chi2 the linearized post-fit chi2,
        chi2r the bases-marginalized chi2 at the point
        (``Residuals.chi2`` semantics), xf the ML basis amplitudes,
        (iters, rel_resid) the CG effort + final worst relative
        residual of the same dispatch (ISSUE 14).

        Health (armed via $PINT_TPU_HEALTH) observes the CG effort
        against its budget through the process ``HealthMonitor``;
        shadow sampling ($PINT_TPU_SHADOW_RATE) replays the SAME
        accumulated state through the numpy CG mirror in a
        background thread and records device-vs-host drift in sigma
        — the state is already host-resident and (p+q)^2-small, so
        the streaming path is the cheapest shadow in the stack."""
        from pint_tpu import obs
        from pint_tpu.obs import health as _health
        from pint_tpu.obs import metrics as om
        from pint_tpu.runtime import get_supervisor

        if budget is None:
            budget = self.default_budget
        sup = get_supervisor()
        sfull = np.asarray(self.meta["sfull"], np.float64)

        def run():
            dev = tuple(jnp.asarray(x) for x in state)
            out = self._jit_final(dev, jnp.asarray(self.phi), jnp.asarray(sfull), jnp.asarray(int(budget), jnp.int32), jnp.asarray(float(tol)))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return tuple(np.asarray(o) for o in out)

        def shadow(out):
            # numpy-mirror replay of the SAME state (deep-copied —
            # the mirror's carry flush mutates); drift = max |d dp|
            # in sigma of the device covariance. A failed CG
            # (ok=False: the caller raises/falls back) is not
            # shadow-applicable — drifting against garbage would be
            # a false verdict on top of the real solver_not_ok one.
            if not bool(np.asarray(out[5])):
                return None
            mirror = [np.array(x) for x in state]
            mdp = acc_finalize_np(
                mirror, self.phi, sfull=sfull,
                incoffset=self.incoffset, budget=budget,
                tol=tol)[0]
            return _health.drift_sigma(out[0], out[1], mdp)

        with obs.span("stream.solve", p=self.p, q=self.q):
            out = sup.dispatch(run, key="stream.solve",
                               shadow=shadow, shadow_kind="stream")
            dp, cov, chi2, chi2r, xf, ok, iters, resid = out
            if observe:
                _health.observe(
                    "stream.solve",
                    {"cg_iters": int(iters),
                     "cg_budget": int(budget),
                     "cg_rel_residual": float(resid),
                     "ok": bool(ok), "chi2": float(chi2r),
                     "values": [dp, chi2]},
                    key="stream.solve")
        om.counter("pint_tpu_stream_cg_solves_total",
                   "streaming-GLS CG finalize dispatches").inc()
        return (np.asarray(dp), np.asarray(cov), float(chi2),
                float(chi2r), np.asarray(xf), bool(ok), int(iters),
                float(resid))

    def noise_realization(self, xf) -> np.ndarray:
        """ML correlated-noise realization F @ xf [s] in the ORIGINAL
        TOA order (undoing the epoch-sort permutation)."""
        noise = self._F @ np.asarray(xf)
        if self._perm is not None:
            out = np.empty_like(noise)
            out[self._perm] = noise
            return out
        return noise

    # -- host mirror ---------------------------------------------------

    def solve_np(self, tol: float = 1e-13):
        """Full host-mirror pass (failover path, 'degraded in speed,
        not correctness'): dense host assembly of the rows at the
        MODEL'S CURRENT parameter point — syncing the model to the
        point being asked about is the caller's job (the failover
        fitter updates the model before every trial pass) — then the
        chunked numpy accumulate + CG finalize."""
        from pint_tpu.residuals import Residuals

        model = self.model
        res = Residuals(self.toas, model, subtract_mean=False)
        M, names, _ = model.designmatrix(self.toas, incoffset=True)
        nvec = model.scaled_toa_uncertainty(self.toas) ** 2
        seg = model.noise_model_ecorr_segments(self.toas)
        if seg is not None:
            eid, jvar, exclude = seg
        else:
            eid, jvar, exclude = None, None, ()
        F = model.noise_model_designmatrix(self.toas,
                                           exclude=exclude)
        phi = model.noise_model_basis_weight(self.toas,
                                             exclude=exclude)
        if F is None:
            F = np.zeros((self.toas.ntoas, 0))
            phi = np.ones(0)
        r0 = np.asarray(res.time_resids)
        if eid is not None and np.any(np.diff(eid) < 0):
            perm = np.argsort(eid, kind="stable")
            M, F, r0, nvec, eid = (M[perm], F[perm], r0[perm],
                                   nvec[perm], eid[perm])
        out = stream_solve_np(M, F, phi, r0, nvec, self.chunk,
                              incoffset=self.incoffset, eid=eid,
                              jvar=jvar, tol=tol)
        dp, cov, chi2, chi2r, xf, ok, iters, resid = out
        return (np.asarray(dp), np.asarray(cov), float(chi2),
                float(chi2r), np.asarray(xf), bool(ok), int(iters),
                float(resid))
