"""Derived astrophysical quantities from timing parameters.

Reference: src/pint/derived_quantities.py (mass_funct, mass_funct2,
companion_mass, pulsar_mass, pulsar_age, pulsar_edot, pulsar_B,
pulsar_B_lightcyl, omdot, gamma, pbdot, shklovskii_factor). All inputs
and outputs are plain floats in the conventional units noted per
function (no astropy in this stack); SI constants are exact IAU/CODATA
values.
"""

from __future__ import annotations

import numpy as np

__all__ = ["mass_funct", "mass_funct2", "companion_mass", "pulsar_mass",
           "p_to_f", "f_to_p", "pulsar_age", "pulsar_edot", "pulsar_B",
           "pulsar_B_lightcyl", "omdot", "gamma", "pbdot", "pmtot",
           "shklovskii_factor"]

C = 299792458.0                  # m/s
TSUN = 4.925490947e-6            # GM_sun/c^3 [s]
GMSUN = TSUN * C ** 3            # m^3/s^2
MSUN_KG = 1.98892e30
SECPERDAY = 86400.0
SECPERYR = 86400.0 * 365.25
I_NS = 1e45 * 1e-7               # 10^45 g cm^2 -> kg m^2
PC_M = 3.0856775814913673e16
MAS_YR_TO_RAD_S = np.pi / 180.0 / 3600.0 / 1000.0 / SECPERYR


def p_to_f(p: float, pd: float = 0.0):
    """(F0, F1) from (P [s], Pdot) (reference: utils.p_to_f)."""
    f0 = 1.0 / p
    return f0, -pd / p ** 2


def f_to_p(f0: float, f1: float = 0.0):
    """(P [s], Pdot) from (F0, F1)."""
    p = 1.0 / f0
    return p, -f1 / f0 ** 2


def mass_funct(pb_days: float, x_lts: float) -> float:
    """Binary mass function [Msun]: 4 pi^2 x^3 / (G Pb^2)
    (reference: derived_quantities.mass_funct)."""
    pb = pb_days * SECPERDAY
    return 4.0 * np.pi ** 2 * x_lts ** 3 / (TSUN * pb ** 2)


def mass_funct2(mp: float, mc: float, i_deg: float) -> float:
    """(mc sin i)^3 / (mp + mc)^2 [Msun] (reference: mass_funct2)."""
    return (mc * np.sin(np.radians(i_deg))) ** 3 / (mp + mc) ** 2


def companion_mass(pb_days: float, x_lts: float, i_deg: float = 90.0,
                   mp: float = 1.4) -> float:
    """Companion mass [Msun] solving the mass function cubic
    (reference: companion_mass; exact real root of
    (mc sin i)^3 = f (mp+mc)^2)."""
    f = mass_funct(pb_days, x_lts)
    sini = np.sin(np.radians(i_deg))
    # solve s^3 mc^3 - f mc^2 - 2 f mp mc - f mp^2 = 0 (one real root)
    coeffs = [sini ** 3, -f, -2.0 * f * mp, -f * mp ** 2]
    roots = np.roots(coeffs)
    real = roots[np.abs(roots.imag) < 1e-9 * np.abs(roots.real + 1e-30)]
    return float(np.max(real.real))


def pulsar_mass(pb_days: float, x_lts: float, mc: float,
                i_deg: float) -> float:
    """Pulsar mass [Msun] given companion mass and inclination
    (reference: pulsar_mass)."""
    f = mass_funct(pb_days, x_lts)
    return float((mc * np.sin(np.radians(i_deg))) ** 1.5 / np.sqrt(f)
                 - mc)


def pulsar_age(f0: float, f1: float, n: int = 3) -> float:
    """Characteristic age [yr]: -f/((n-1) fdot) (reference:
    pulsar_age; n = braking index)."""
    return float(-f0 / ((n - 1) * f1) / SECPERYR)


def pulsar_edot(f0: float, f1: float, I: float = I_NS) -> float:
    """Spin-down luminosity [W]: -4 pi^2 I f fdot (reference:
    pulsar_edot)."""
    return float(-4.0 * np.pi ** 2 * I * f0 * f1)


def pulsar_B(f0: float, f1: float) -> float:
    """Surface dipole field [Gauss]: 3.2e19 sqrt(-pdot p)
    (reference: pulsar_B)."""
    p, pd = f_to_p(f0, f1)
    return float(3.2e19 * np.sqrt(-pd * p if pd < 0 else pd * p))


def pulsar_B_lightcyl(f0: float, f1: float) -> float:
    """Field at the light cylinder [Gauss] (reference:
    pulsar_B_lightcyl): 2.9e8 p^-5/2 sqrt(pdot)."""
    p, pd = f_to_p(f0, f1)
    return float(2.9e8 * abs(pd) ** 0.5 * p ** -2.5)


def omdot(mp: float, mc: float, pb_days: float, e: float) -> float:
    """GR periastron advance [deg/yr] (reference: omdot)."""
    n = 2.0 * np.pi / (pb_days * SECPERDAY)
    m = TSUN * (mp + mc)
    rate = 3.0 * n ** (5.0 / 3.0) * m ** (2.0 / 3.0) / (1.0 - e ** 2)
    return float(np.degrees(rate) * SECPERYR)


def gamma(mp: float, mc: float, pb_days: float, e: float) -> float:
    """GR Einstein-delay amplitude [s] (reference: gamma):
    e n^-1/3 m2 (m1 + 2 m2) M^-4/3, masses in time units."""
    n = 2.0 * np.pi / (pb_days * SECPERDAY)
    m1, m2 = TSUN * mp, TSUN * mc
    m = m1 + m2
    return float(e * n ** (-1.0 / 3.0) * m2 * (m1 + 2.0 * m2)
                 * m ** (-4.0 / 3.0))


def pbdot(mp: float, mc: float, pb_days: float, e: float) -> float:
    """GR orbital decay rate [s/s] (reference: pbdot)."""
    n = 2.0 * np.pi / (pb_days * SECPERDAY)
    m1, m2 = TSUN * mp, TSUN * mc
    m = m1 + m2
    fe = (1.0 + 73.0 / 24.0 * e ** 2 + 37.0 / 96.0 * e ** 4) \
        * (1.0 - e ** 2) ** -3.5
    return float(-(192.0 * np.pi / 5.0) * n ** (5.0 / 3.0) * m1 * m2
                 * m ** (-1.0 / 3.0) * fe)


def pmtot(model) -> float:
    """Total proper motion [mas/yr] from the model's astrometry
    (reference: derived_quantities.pmtot): quadrature sum of the
    equatorial (PMRA, PMDEC) or ecliptic (PMELONG, PMELAT) pair —
    both conventions carry the cos(latitude) factor already."""
    for a, b in (("PMRA", "PMDEC"), ("PMELONG", "PMELAT")):
        try:
            va = model.get_param(a).value
            vb = model.get_param(b).value
        except KeyError:
            continue
        return float(np.hypot(va or 0.0, vb or 0.0))
    raise ValueError("model has no proper-motion parameters")


def shklovskii_factor(pm_mas_yr: float, d_kpc: float) -> float:
    """Shklovskii apparent-acceleration factor a_s = mu^2 d / c [1/s]
    (multiply by P to get the apparent Pdot contribution; reference:
    shklovskii_factor)."""
    mu = pm_mas_yr * MAS_YR_TO_RAD_S
    return float(mu ** 2 * d_kpc * 1.0e3 * PC_M / C)
