"""Build-time unit discipline (SURVEY §5, last open row).

The reference leans on astropy.units at runtime; a TPU-first design
cannot afford unit objects on device arrays (they would block fusion
and add per-op host work), so units live ENTIRELY at build/trace time:

- every Parameter carries a ``units`` string (par-file units — these
  define the design-matrix column units, reference:
  TimingModel.designmatrix);
- ``ToaBatch.UNITS`` documents the unit of every batch leaf;
- each Component family declares the expected DIMENSION of its
  parameters (``Component.param_dimensions``), and
  ``check_model_units`` verifies, at model-build time, that every
  device parameter's unit string parses and matches the declared
  dimension. A component wired with wrong units (PB in seconds, an
  epoch in years, a frequency-derivative ladder off by one power of
  time) fails with a clear UnitError before anything is traced.

The algebra is deliberately tiny: dimensions over (time, length,
angle, mass, electron-column), exact rational exponents, and a parser
for the compound forms used in par files ("pc cm^-3", "Hz/s^2",
"mas/yr", "1/s^2", "lt-s/s"). No conversions happen here — device code
converts explicitly at its boundaries (that design is what keeps the
XLA graphs clean); this layer only guarantees the declarations agree.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, Optional, Tuple

__all__ = ["Unit", "UnitError", "parse_unit", "check_model_units",
           "DIMENSIONLESS"]


class UnitError(ValueError):
    """A unit string failed to parse or a dimension check failed."""


# base dimensions: (time, length, angle, mass, electron column dens.)
_DIMS = ("T", "L", "A", "M", "NE")

# atom -> dimension exponents (no scale factors: this layer checks
# dimensions, not magnitudes)
_ATOMS: Dict[str, Dict[str, Fraction]] = {
    "s": {"T": Fraction(1)},
    "ms": {"T": Fraction(1)},
    "us": {"T": Fraction(1)},
    "ns": {"T": Fraction(1)},
    "sec": {"T": Fraction(1)},
    "second": {"T": Fraction(1)},
    "d": {"T": Fraction(1)},
    "day": {"T": Fraction(1)},
    "mjd": {"T": Fraction(1)},
    "yr": {"T": Fraction(1)},
    "year": {"T": Fraction(1)},
    "hz": {"T": Fraction(-1)},
    "mhz": {"T": Fraction(-1)},
    "ghz": {"T": Fraction(-1)},
    "m": {"L": Fraction(1)},
    "km": {"L": Fraction(1)},
    "cm": {"L": Fraction(1)},
    "au": {"L": Fraction(1)},
    "pc": {"L": Fraction(1)},
    "kpc": {"L": Fraction(1)},
    "ls": {"T": Fraction(1)},      # light-second: time-valued length
    "lt-s": {"T": Fraction(1)},
    "rad": {"A": Fraction(1)},
    "deg": {"A": Fraction(1)},
    "arcsec": {"A": Fraction(1)},
    "mas": {"A": Fraction(1)},
    "uas": {"A": Fraction(1)},
    "h:m:s": {"A": Fraction(1)},   # sexagesimal RA (par I/O converts)
    "d:m:s": {"A": Fraction(1)},
    "hourangle": {"A": Fraction(1)},
    "turn": {"A": Fraction(1)},
    "cycle": {"A": Fraction(1)},
    "msun": {"M": Fraction(1)},
    "kg": {"M": Fraction(1)},
    "strain": {},          # dimensionless (GW convention)
    "1": {},
    "": {},
}


class Unit:
    """A pure dimension vector with exact rational exponents."""

    __slots__ = ("dims",)

    def __init__(self, dims: Optional[Dict[str, Fraction]] = None):
        self.dims = {k: v for k, v in (dims or {}).items() if v != 0}

    def __mul__(self, other: "Unit") -> "Unit":
        out = dict(self.dims)
        for k, v in other.dims.items():
            out[k] = out.get(k, Fraction(0)) + v
        return Unit(out)

    def __truediv__(self, other: "Unit") -> "Unit":
        return self * other ** -1

    def __pow__(self, n) -> "Unit":
        f = Fraction(n)
        return Unit({k: v * f for k, v in self.dims.items()})

    def __eq__(self, other) -> bool:
        return isinstance(other, Unit) and self.dims == other.dims

    def __hash__(self):
        return hash(tuple(sorted(self.dims.items())))

    def __repr__(self):
        if not self.dims:
            return "Unit(1)"
        parts = [f"{k}^{v}" if v != 1 else k
                 for k, v in sorted(self.dims.items())]
        return "Unit(" + " ".join(parts) + ")"


DIMENSIONLESS = Unit()


def _parse_atom(tok: str) -> Unit:
    """One factor: ``atom`` or ``atom^exp`` (exp may be negative or
    fractional like 2/3). ``sqrt(X)`` is X^(1/2); ``log10`` /
    ``log10(X)`` is dimensionless (a logarithm)."""
    tok = tok.strip()
    if not tok:
        return DIMENSIONLESS
    low = tok.lower()
    if low == "log10" or (low.startswith("log10(")
                          and low.endswith(")")):
        return DIMENSIONLESS
    if low.startswith("sqrt(") and low.endswith(")"):
        return _parse_atom(tok[5:-1]) ** Fraction(1, 2)
    if "^" in tok:
        base, exp = tok.split("^", 1)
    elif tok[-1].isdigit() and tok[:-2] and tok[-2] in "-+" \
            and tok[:-2].lower() in _ATOMS:
        base, exp = tok[:-2], tok[-2:]      # "cm-3" style
    elif tok[-1].isdigit() and tok[:-1].lower() in _ATOMS:
        base, exp = tok[:-1], tok[-1]        # "s2" style
    else:
        base, exp = tok, "1"
    b = base.strip().lower()
    if b not in _ATOMS:
        raise UnitError(f"unknown unit atom {base!r} in {tok!r}")
    try:
        e = Fraction(exp.strip())
    except (ValueError, ZeroDivisionError) as err:
        raise UnitError(f"bad exponent {exp!r} in {tok!r}") from err
    return Unit(dict(_ATOMS[b])) ** e


def parse_unit(text: Optional[str]) -> Unit:
    """Parse a par-file unit string to its dimension. Handles the
    forms parameters actually use: "pc cm^-3 / yr^2", "Hz/s^2",
    "mas/yr", "1/s^2", "lt-s/s", "", None."""
    if text is None:
        return DIMENSIONLESS
    text = text.strip()
    if not text:
        return DIMENSIONLESS
    out = DIMENSIONLESS
    # split on '/' first: everything after each '/' divides
    num, *dens = text.split("/")
    for tok in num.replace("·", " ").replace("*", " ").split():
        out = out * _parse_atom(tok)
    for den in dens:
        for i, tok in enumerate(
                den.replace("·", " ").replace("*", " ").split()):
            out = out / _parse_atom(tok)
    return out


# convenience dimensions for specs
TIME = parse_unit("s")
ANGLE = parse_unit("rad")
FREQ = parse_unit("Hz")
NE_COL = parse_unit("pc cm^-3")
MASS = parse_unit("Msun")


def check_model_units(model) -> None:
    """Walk every component's declared parameter dimensions and verify
    each device parameter's unit string agrees. Raises UnitError with
    the component, parameter, declared and expected units. Called from
    TimingModel.validate (build time — zero trace/runtime cost)."""
    for cname, comp in model.components.items():
        spec = comp.param_dimensions()
        if not spec:
            continue
        for pname, p in comp.params.items():
            expected = _spec_lookup(spec, pname)
            if callable(expected):
                expected = expected(pname)
            if expected is None:
                continue
            try:
                got = parse_unit(getattr(p, "units", None))
            except UnitError as e:
                raise UnitError(
                    f"{cname}.{pname}: unparseable units "
                    f"{p.units!r}: {e}") from e
            if got != expected:
                raise UnitError(
                    f"{cname}.{pname}: declared units {p.units!r} "
                    f"have dimension {got}, but this slot requires "
                    f"{expected} — seconds/days/frequency mixups are "
                    f"exactly what this check exists to catch")


def _spec_lookup(spec: Dict[str, Unit], pname: str):
    """Exact name match, else the longest matching 'PREFIX*' entry
    (the '*' part must be numeric, possibly after an underscore)."""
    if pname in spec:
        return spec[pname]
    best = None
    for key, dim in spec.items():
        if not key.endswith("*"):
            continue
        stem = key[:-1]
        if pname.startswith(stem):
            rest = pname[len(stem):].lstrip("_")
            if rest.isdigit() and (best is None or
                                   len(stem) > best[0]):
                best = (len(stem), dim)
    return best[1] if best else None
