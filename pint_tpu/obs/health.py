"""Numerical-health plane: in-trace solver telemetry evaluated on
host, shadow-oracle drift sampling, numerical-incident forensics
(ISSUE 14 tentpole).

The stack's correctness story rests on numerics the runtime could
not see: TPU f64 is emulated and not correctly rounded (~2^-48,
CLAUDE.md), f32 demotions are gated statically (graftlint G9) but
never observed in production, and the streaming path's matrix-free
CG computes its iteration count and final residual on device and —
before this module — threw them away. This module is the organ that
watches those numbers continuously:

- **in-trace health vectors**: every major device kernel (fit step /
  whole-fit loop, streaming chunk accumulator, CG finalize, GLS/WLS/
  wideband solves, MCMC chain chunks, serve slot kernels) can return
  a handful of cheap in-kernel reductions — non-finite counts, max
  |whitened residual|, CG iterations-used + final relative residual,
  Cholesky ``ok`` flags, streaming colmax rescale magnitude, chi2,
  acceptance counts — as EXTRA SCALARS of the existing dispatch, so
  health costs zero additional dispatches. The taps are gated by
  ``config.health_enabled`` as a STATIC build flag (part of the
  compile key, like donation): disarmed, they compile to nothing and
  the executables are the pre-health ones.

- **HealthMonitor.observe** is the ONE host-side consumer (graftlint
  G14 bans ad-hoc health math at call sites): it evaluates each
  vector against the validated ``$PINT_TPU_HEALTH*`` thresholds
  (``config.health_*`` — never raw env reads), feeds the registry
  gauges/histograms (``pint_tpu_health_*``), attaches a ``health``
  child event to the enclosing dispatch span (the G12 span the call
  site already holds), and tracks the worst recent verdict per
  (pool, kind) for ``/healthz`` and the inline ``stats`` answer.

- **incidents**: NaN/Inf appearance, CG budget exhaustion, chi2
  blow-up, residuals past the garbage threshold, or shadow drift
  beyond band fire a rate-limited ``numerics:<reason>`` flight dump
  (the FlightRecorder's per-reason rate limit gives "exactly one per
  episode") — forensics for *why a number went bad*, pairing with
  the request journal exactly the way breaker-open dumps do.

- **shadow-oracle drift sampling** (``$PINT_TPU_SHADOW_RATE``,
  default off): every Nth successful supervised dispatch of a
  shadow-capable key replays the completed solve on the existing
  numpy mirrors in a BACKGROUND daemon thread and records
  device-vs-host drift in sigma as a registry histogram — the
  production answer to "is emulated f64 still holding" that makes
  on-chip captures past the 131k dense-oracle ceiling trustworthy.
  The scheduler lives in ``runtime.DispatchSupervisor`` (the
  ``shadow=`` dispatch argument); this module owns the rate
  counter, the thread, the recording and the drift verdict.

Everything host-side here is pure stdlib + the obs registry; the
disarmed fast path is one attribute read and a branch per observe
(the tracer-off discipline). Histogram rows are ``obs.hist``
log2-bucket rows — unit-agnostic: CG-iteration rows count
iterations in the "us" slot, drift rows record MICRO-SIGMA per "us"
(so a ``p99_ms`` readback is milli-sigma), documented here because
the bucket math is shared with the latency rows.
"""

from __future__ import annotations

import threading

from pint_tpu.runtime import locks
import time
from typing import Callable, Dict, Optional, Tuple

__all__ = ["HealthMonitor", "get_monitor", "configure", "reset",
           "observe", "status", "drift_sigma"]


def drift_sigma(dev_x, dev_cov, mirror_x) -> float:
    """THE device-vs-mirror drift definition (in sigma of the DEVICE
    covariance; zero/invalid sigmas guard to 1.0 so a pinned column
    cannot divide-by-zero a verdict) — every shadow closure computes
    its drift through here, so the vocabulary has one tested home
    (the G14 rationale) and the dense/streaming shadows can never
    diverge."""
    import numpy as np

    sig = np.sqrt(np.abs(np.diagonal(np.asarray(dev_cov))))
    sig = np.where(sig > 0, sig, 1.0)
    return float(np.max(
        np.abs(np.asarray(dev_x) - np.asarray(mirror_x)) / sig))

# incident taxonomy (the <reason> of numerics:<reason> flight dumps)
REASONS = ("nonfinite", "cg_budget", "chi2_blowup", "resid_sigma",
           "solver_not_ok", "drift")

# a bad (pool, kind) verdict sticks — degrading /healthz to 503 —
# until it is this old AND a newer good observation has landed: long
# enough that a flapping numerics episode stays visible to probes,
# bounded so one transient incident cannot evict a recovered worker
# forever (the breaker-cooldown shape)
_WORST_TTL_S = 300.0


def _nonfinite_count(vals) -> int:
    """Count non-finite entries across scalars/arrays — the ONE
    place host-side non-finite math for health lives (G14)."""
    import numpy as np

    n = 0
    for v in vals:
        if v is None:
            continue
        a = np.asarray(v)
        if a.dtype.kind not in "fc":
            continue
        n += int(a.size - np.count_nonzero(np.isfinite(a)))
    return n


class HealthMonitor:
    """Process numerical-health evaluator (module docstring).

    One instance per process (``get_monitor``); ``obs.reset()``
    drops it with the tracer/registry so a configured monitor never
    leaks across tests. All counters/gauges are bound children of
    the process metric registry, so ``status()`` is a derived view
    (the registry-vs-snapshot parity discipline of ISSUE 11)."""

    def __init__(self, enabled: Optional[bool] = None,
                 shadow_rate: Optional[int] = None):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        self.enabled = config.health_enabled(enabled)
        self.shadow_rate = config.shadow_rate() \
            if shadow_rate is None else max(0, int(shadow_rate))
        self.chi2_factor = config.health_chi2_factor()
        self.resid_band = config.health_resid_sigma()
        self.cg_frac = config.health_cg_budget_frac()
        self._lock = locks.make_lock("obs.health")
        self._shadow_seen: Dict[str, int] = {}
        self._worst: Dict[Tuple[str, str], dict] = {}
        self.last_incident: Optional[dict] = None
        self._c_incidents = om.counter(
            "pint_tpu_health_incidents_total",
            "numerical-health incidents by (kind, reason)")
        self._c_shadow = om.counter(
            "pint_tpu_health_shadow_replays_total",
            "shadow-oracle background replays")
        self._c_drift_exceeded = om.counter(
            "pint_tpu_health_shadow_drift_exceeded_total",
            "shadow replays whose drift exceeded the band")
        self._c_cg_exhausted = om.counter(
            "pint_tpu_health_cg_budget_exhausted_total",
            "CG solves that hit their iteration budget")
        self._g_last = om.gauge(
            "pint_tpu_health_last_value",
            "last observed health signal per (kind, signal)")
        self._h_cg = om.histogram(
            "pint_tpu_health_cg_iters",
            "CG iterations used (log2 buckets, unit = iterations)")
        self._h_drift = om.histogram(
            "pint_tpu_health_drift_sigma",
            "device-vs-host shadow drift (log2 buckets, unit = "
            "MICRO-sigma; p99_ms readback = milli-sigma)")

    @property
    def drift_band(self) -> float:
        """Re-resolved per read, NOT cached at construction: the
        route-aware auto default depends on the jax backend, and a
        monitor built by an early /healthz scrape (before any
        dispatch initialized the backend) would otherwise freeze
        the tight f64 band on a TPU worker — flapping /healthz on
        its own sanctioned f32 quantization forever. Drift
        observations are rare (1-in-N background replays), so the
        re-read costs nothing that matters."""
        from pint_tpu import config

        return config.health_drift_sigma()

    # -- the tap consumer ---------------------------------------------

    def observe(self, kind: str, signals: dict, *,
                pool: str = "device", key: Optional[str] = None) -> dict:
        """Evaluate one kernel's health signals; returns the verdict
        ``{"ok": bool, "reasons": [...], "checked": bool}``.

        ``signals`` is a dict of named taps — recognized keys:

        - ``hv``: the in-trace vector of the fit kernels,
          ``[nonfinite_count, max_resid_sigma, chi2]``;
        - ``values``: iterable of host scalars/arrays whose
          non-finite count is taken here (the injected-NaN readback
          check on already-returned outputs — zero extra dispatches);
        - ``chi2`` / ``chi2_prev``: blow-up detection;
        - ``cg_iters`` / ``cg_budget`` / ``cg_rel_residual`` /
          ``ok``: solver-effort and solver-verdict taps;
        - ``max_resid_sigma``, ``rescale``, ``accept_frac``,
          ``drift_sigma``: recorded + thresholded where a band
          exists.

        Disarmed, this returns immediately (one branch) and records
        NOTHING — the off-path zero-record contract. Exception: a
        ``drift_sigma`` observation is armed by the SHADOW rate
        alone — $PINT_TPU_SHADOW_RATE without $PINT_TPU_HEALTH is a
        documented configuration (drift sampling only), and a replay
        whose drift silently vanished would burn host CPU for
        nothing."""
        if not self.enabled and not (
                self.shadow_rate and "drift_sigma" in signals):
            return {"ok": True, "checked": False}
        import math

        import numpy as np

        vals: dict = {}
        reasons = []
        hv = signals.get("hv")
        if hv is not None:
            a = np.asarray(hv, dtype=np.float64).reshape(-1)
            vals["nonfinite"] = 0 if math.isfinite(float(a[0])) \
                else 1
            if math.isfinite(float(a[0])):
                vals["nonfinite"] = int(a[0])
            if a.size > 1:
                vals["max_resid_sigma"] = float(a[1])
            if a.size > 2 and "chi2" not in signals:
                vals["chi2"] = float(a[2])
            if a.size > 3 and "cg_rel_residual" not in signals:
                # slot 3 (the dense-solve hv): relative residual of
                # the direct solve — same gauge family as CG's
                vals["cg_rel_residual"] = float(a[3])
        if "values" in signals:
            vals["nonfinite"] = vals.get("nonfinite", 0) + \
                _nonfinite_count(signals["values"])
        if signals.get("nonfinite") is not None:
            # a precomputed in-trace count (the streaming chunk tap)
            pre = float(np.asarray(signals["nonfinite"]))
            vals["nonfinite"] = vals.get("nonfinite", 0) + \
                (int(pre) if math.isfinite(pre) else 1)
        if "lnpost" in signals:
            # walker log-posteriors: -inf is a LEGAL value (a walker
            # parked in a zero-probability region until its first
            # accepted move — the sampler only requires SOME finite
            # walker), so only NaN/+inf count as numerics garbage
            a = np.asarray(signals["lnpost"])
            vals["nonfinite"] = vals.get("nonfinite", 0) + \
                int(np.isnan(a).sum() + np.isposinf(a).sum())
        for name in ("chi2", "chi2_prev", "cg_iters", "cg_budget",
                     "cg_rel_residual", "max_resid_sigma",
                     "rescale", "accept_frac", "drift_sigma"):
            if signals.get(name) is not None:
                vals[name] = float(np.asarray(signals[name]))
        ok_flag = signals.get("ok")

        nf = vals.get("nonfinite", 0)
        if nf and not math.isfinite(float(nf)):
            nf = 1
        nf = int(nf)
        vals["nonfinite"] = nf
        if nf > 0:
            reasons.append("nonfinite")
        chi2 = vals.get("chi2")
        if chi2 is not None and not math.isfinite(chi2):
            if "nonfinite" not in reasons:
                reasons.append("nonfinite")
        prev = vals.get("chi2_prev")
        if chi2 is not None and prev is not None and \
                math.isfinite(chi2) and math.isfinite(prev) and \
                prev > 0 and chi2 > self.chi2_factor * prev:
            reasons.append("chi2_blowup")
        mrs = vals.get("max_resid_sigma")
        if mrs is not None and (not math.isfinite(mrs)
                                or mrs > self.resid_band):
            if math.isfinite(mrs) or nf == 0:
                reasons.append("resid_sigma" if math.isfinite(mrs)
                               else "nonfinite")
        iters = vals.get("cg_iters")
        budget = vals.get("cg_budget")
        if iters is not None:
            if math.isfinite(iters):
                self._h_cg.row(kind=kind).record(iters * 1e-6)
            if budget is not None and budget > 0 and \
                    iters >= self.cg_frac * budget:
                self._c_cg_exhausted.inc(kind=kind)
                reasons.append("cg_budget")
        if ok_flag is not None and not bool(np.asarray(ok_flag)):
            reasons.append("solver_not_ok")
        drift = vals.get("drift_sigma")
        if drift is not None:
            # finiteness BEFORE the histogram: a non-finite drift is
            # exactly the failure the shadow exists to catch, and it
            # must land as an incident, not as an OverflowError
            # inside the log2 bucketing that kills the verdict
            if math.isfinite(drift):
                self._h_drift.row(kind=kind).record(drift)
            if not math.isfinite(drift) or drift > self.drift_band:
                self._c_drift_exceeded.inc(kind=kind)
                reasons.append("drift")
        # de-dup, first reason is the headline
        seen: list = []
        for r in reasons:
            if r not in seen:
                seen.append(r)
        reasons = seen
        for name, v in vals.items():
            if name in ("nonfinite", "chi2", "chi2_prev",
                        "max_resid_sigma", "cg_iters",
                        "cg_rel_residual", "rescale",
                        "accept_frac", "drift_sigma") and \
                    math.isfinite(float(v)):
                self._g_last.set(float(v), kind=kind, signal=name)
        verdict = {"ok": not reasons, "reasons": reasons,
                   "checked": True}
        self._note_verdict(pool, kind, verdict)
        from pint_tpu import obs

        obs.event("health", kind=kind, pool=pool, key=key,
                  ok=not reasons,
                  reasons=",".join(reasons) if reasons else None,
                  **{k: round(float(v), 6) for k, v in vals.items()
                     if math.isfinite(float(v))})
        if reasons:
            self._incident(kind, reasons[0], pool=pool, key=key,
                           signals=vals, reasons=reasons)
        return verdict

    # -- shadow-oracle sampling ---------------------------------------

    def shadow_due(self, key: str) -> bool:
        """Deterministic 1-in-N gate per dispatch key (the
        supervisor's shadow scheduler consults this on every
        successful shadow-capable dispatch). The FIRST eligible
        dispatch per key replays (a session that never reaches N
        dispatches still produces drift evidence)."""
        if not self.shadow_rate:
            return False
        with self._lock:
            n = self._shadow_seen.get(key, 0)
            self._shadow_seen[key] = n + 1
        return n % self.shadow_rate == 0

    def shadow_replay(self, kind: str, key: str,
                      fn: Callable[[], Optional[float]],
                      wait: bool = False):
        """Run one shadow replay — ``fn`` re-solves on the numpy
        mirror and returns device-vs-host drift in sigma (None =
        mirror not applicable). Background daemon thread by default
        (the production path must never serialize a dispatch behind
        a host replay); ``wait=True`` is the deterministic test
        mode. Never raises: a broken mirror is counted and logged,
        not a new failure mode on the hot path."""

        def work():
            try:
                drift = fn()
            except Exception as e:
                try:
                    from pint_tpu.logging import log

                    log.warning("shadow replay (%s) failed: %r",
                                key, e)
                except Exception:
                    pass
                # a replay that RAN and died still counts: pollers
                # (bench, the capture stage) wait on this counter —
                # without it a broken mirror stalls them to timeout
                self._c_shadow.inc(kind=kind)
                return
            if drift is not None:
                self.observe(kind, {"drift_sigma": float(drift)},
                             pool="shadow", key=key)
            # counted AFTER the observation lands: pollers (bench,
            # the capture stage, tests) wait on this counter and
            # then read the drift histogram — incrementing first
            # would open a gap where the replay "happened" but its
            # sample is not yet visible
            self._c_shadow.inc(kind=kind)

        if wait:
            work()
            return None
        t = threading.Thread(target=work, daemon=True,
                             name=f"pint-shadow-{kind}")
        t.start()
        return t

    # -- incidents / reporting ----------------------------------------

    def _note_verdict(self, pool: str, kind: str, verdict: dict):
        now = time.monotonic()
        with self._lock:
            cur = self._worst.get((pool, kind))
            rec = {"ok": verdict["ok"],
                   "reasons": list(verdict["reasons"]), "t": now}
            # "worst RECENT": a bad verdict sticks through good
            # observations until it has aged past the TTL — then the
            # next good observation clears it (so a transient
            # incident degrades /healthz for at most ~TTL, never for
            # the life of the process), while a bad verdict with no
            # later good evidence stays visible indefinitely
            if cur is None or not verdict["ok"] or cur["ok"] or \
                    now - cur["t"] >= _WORST_TTL_S:
                self._worst[(pool, kind)] = rec
            else:
                cur["last_good_t"] = rec["t"]

    def _incident(self, kind: str, reason: str, pool: str,
                  key: Optional[str], signals: dict, reasons: list):
        import math

        self._c_incidents.inc(kind=kind, reason=reason)
        with self._lock:
            self.last_incident = {"kind": kind, "reason": reason,
                                  "reasons": list(reasons),
                                  "pool": pool, "key": key,
                                  "t": time.monotonic()}
        from pint_tpu import obs

        obs.event("health.incident", kind=kind, reason=reason,
                  pool=pool, key=key)
        # rate-limited per reason by the FlightRecorder itself —
        # a NaN storm writes one dump per min_interval_s, not one
        # per dispatch
        obs.flight_dump(
            f"numerics:{reason}", kind=kind, pool=pool, key=key,
            signals={k: (float(v) if math.isfinite(float(v))
                         else repr(float(v)))
                     for k, v in signals.items()})
        try:
            from pint_tpu.logging import log

            log.warning("numerical-health incident %s at %s/%s "
                        "(pool %s): %s", reason, kind, key, pool,
                        {k: float(v) for k, v in signals.items()})
        except Exception:
            pass

    def status(self) -> dict:
        """The ``health`` block serve snapshots / healthz / stats
        embed: worst recent verdict per (pool, kind), last incident
        reason + age, counters — all derived from registry children
        + the monitor's own lock (NEVER an engine lock)."""
        now = time.monotonic()
        with self._lock:
            worst = {}
            for (pool, kind), rec in sorted(self._worst.items()):
                e = {"ok": rec["ok"], "reasons": rec["reasons"],
                     "age_s": round(now - rec["t"], 3)}
                if rec.get("last_good_t") is not None:
                    # a bad verdict with later good evidence: still
                    # inside the TTL window, recovery in progress
                    e["last_good_age_s"] = round(
                        now - rec["last_good_t"], 3)
                worst[f"{pool}/{kind}"] = e
            li = None
            if self.last_incident is not None:
                li = {k: v for k, v in self.last_incident.items()
                      if k != "t"}
                li["age_s"] = round(now - self.last_incident["t"], 3)
        out = {
            "armed": self.enabled,
            "shadow_rate": self.shadow_rate,
            "drift_band_sigma": self.drift_band,
            "incidents": int(self._c_incidents.total()),
            "shadow_replays": int(self._c_shadow.total()),
            "shadow_drift_exceeded":
                int(self._c_drift_exceeded.total()),
            "cg_budget_exhausted": int(self._c_cg_exhausted.total()),
            "worst": worst,
            "last_incident": li,
        }
        drift_rows = self._h_drift.rows()
        if drift_rows:
            # micro-sigma buckets: p99_ms readback = milli-sigma
            out["drift"] = {
                "/".join(v for _, v in k) or "_": h.snapshot()
                for k, h in drift_rows}
        cg_rows = self._h_cg.rows()
        if cg_rows:
            out["cg_iters"] = {
                "/".join(v for _, v in k) or "_": h.snapshot()
                for k, h in cg_rows}
        return out


# ------------------------------------------------------------------
# the process-global monitor (armed by env, like the tracer)
# ------------------------------------------------------------------

_MON: Optional[HealthMonitor] = None
_LOCK = locks.make_lock("obs.health_global")


def get_monitor() -> HealthMonitor:
    global _MON
    if _MON is None:
        with _LOCK:
            if _MON is None:
                _MON = HealthMonitor()
    return _MON


def configure(enabled: Optional[bool] = None,
              shadow_rate: Optional[int] = None) -> HealthMonitor:
    """Explicitly (re)build the global monitor (tests, the bench
    armed leg). Omitted arguments fall back to env/config."""
    global _MON
    with _LOCK:
        _MON = HealthMonitor(enabled=enabled,
                             shadow_rate=shadow_rate)
        return _MON


def reset():
    """Drop the global monitor; the next use re-reads the env (the
    ``obs.reset()`` isolation contract — obs.reset calls this)."""
    global _MON
    with _LOCK:
        _MON = None


def observe(kind: str, signals: dict, *, pool: str = "device",
            key: Optional[str] = None) -> dict:
    """Module-level convenience: ``get_monitor().observe(...)`` —
    THE instrumentation surface call sites use (graftlint G14)."""
    m = _MON
    if m is None:
        m = get_monitor()
    if not m.enabled:   # one attribute read + branch when disarmed
        return {"ok": True, "checked": False}
    return m.observe(kind, signals, pool=pool, key=key)


def status() -> Optional[dict]:
    """The ``health`` block, or None when the monitor is not armed
    (keeps pre-health snapshot shapes bit-compatible). An armed env
    with no observation yet still reports the (empty) block — the
    monitor is built on demand, so a freshly started daemon's first
    ``stats`` answer already says "armed, zero incidents" instead
    of null."""
    m = _MON
    if m is None:
        from pint_tpu import config

        if not (config.health_enabled() or config.shadow_rate()):
            return None
        m = get_monitor()
    if not (m.enabled or m.shadow_rate):
        return None
    return m.status()
