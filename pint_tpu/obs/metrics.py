"""Process-global typed metric registry + Prometheus exposition
(ISSUE 11).

Before this module the serve/dispatch stack's counters lived in four
private snapshot dicts (``RuntimeMetrics``, ``ServeMetrics``, the
admission controller, the capacity router), visible only at
``stop()``/bench time — a latency regression or shed creep was
invisible until a breaker opened or a human read an artifact, and
the multi-worker fleet of ROADMAP item 3 has no pull surface at all.
This module is the metrics *plane* those consumers now write
through:

- **typed metrics**: ``Counter`` (monotonic), ``Gauge`` (set/pull),
  ``Histogram`` (rows are ``obs.hist.LatencyHistogram`` — the same
  power-of-two buckets, O(1) memory, upper-edge quantiles). Every
  metric holds one value per LABEL SET (``(pool, kind, shape_class)``
  on the serve histograms, ``scope`` everywhere an engine-local
  counter must stay distinguishable from another engine's);
- **derived views**: the existing ``snapshot()`` dicts of the
  supervisor/admission/router/serve layers are now read THROUGH
  bound registry children, so artifact blocks stay bit-compatible
  while the registry is the single source of truth (parity asserted
  by tests/test_metrics.py and the chaos oracle);
- **exposition**: ``render()`` emits Prometheus text format 0.0.4
  (`# HELP`/`# TYPE`, cumulative ``_bucket{le=...}`` rows for
  histograms); ``MetricsServer`` serves it on ``/metrics`` plus a
  ``/healthz`` breaker/pool-state JSON from a stdlib ``http.server``
  daemon thread — and NEVER takes an engine lock (the fleet-
  readiness contract: a scrape must not perturb admission or an
  in-flight drain; registry reads hold only per-metric locks);
- **process scope**: one registry per process (``get_registry``),
  ``reset()`` swaps in a fresh one for test isolation (the
  ``obs.reset()`` pattern — consumers built before the reset keep
  mutating their old bound children, invisible to the new registry,
  exactly like a reconfigured tracer).

Everything here is pure stdlib (importable without jax — the breaker
and journal layers keep the same property); the one jax touch,
``sample_device_memory``, refuses to INITIALIZE a backend (peeking
an already-built client only — a wedged axon tunnel hangs backend
init with no error, CLAUDE.md gotchas).
"""

from __future__ import annotations

import itertools
import json
import re
import threading

from pint_tpu.runtime import locks
from typing import Callable, Dict, List, Optional, Tuple

from pint_tpu.obs.hist import LatencyHistogram

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry",
           "MetricsServer", "get_registry", "counter", "gauge",
           "histogram", "new_scope", "reset", "render",
           "default_health", "sample_device_memory"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")

# scope ids are process-monotonic and survive registry resets, so an
# instance built before a reset() can never collide with one built
# after (same reason tracer trace-ids never reset mid-process)
_SCOPE_IDS = itertools.count(1)


def new_scope(prefix: str) -> str:
    """Unique per-instance scope label value (``sup3``, ``adm7``):
    several engines coexist in one process, each with self-contained
    accounting, while the registry stays process-global."""
    return f"{prefix}{next(_SCOPE_IDS)}"


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                extra: Optional[List[Tuple[str, str]]] = None) -> str:
    items = list(key) + list(extra or [])
    if not items:
        return ""
    parts = []
    for k, v in items:
        k = _LABEL_BAD.sub("_", k)
        v = v.replace("\\", r"\\").replace('"', r'\"') \
             .replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Bound:
    """A metric bound to one label set — the hot-path handle the
    supervisor/serve counters hold, so a bump is one lock + one dict
    write with the label key pre-computed."""

    __slots__ = ("metric", "key")

    def __init__(self, metric, key):
        self.metric = metric
        self.key = key

    def inc(self, n: float = 1):
        self.metric._inc(self.key, n)

    def set(self, v: float):
        self.metric._set(self.key, v)

    def value(self) -> float:
        return self.metric._get(self.key)


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = _NAME_BAD.sub("_", name)
        self.help = help
        self._lock = locks.make_plane_lock("obs.metric")
        self._vals: Dict[tuple, float] = {}

    def child(self, **labels) -> _Bound:
        key = _label_key(labels)
        with self._lock:
            self._vals.setdefault(key, 0.0)
        return _Bound(self, key)

    def _inc(self, key, n):
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def _set(self, key, v):
        with self._lock:
            self._vals[key] = float(v)

    def _get(self, key) -> float:
        with self._lock:
            return self._vals.get(key, 0.0)

    # -- views ---------------------------------------------------------

    def series(self) -> List[Tuple[tuple, float]]:
        with self._lock:
            return sorted(self._vals.items())

    def value(self, **labels) -> float:
        return self._get(_label_key(labels))

    def total(self) -> float:
        with self._lock:
            return float(sum(self._vals.values()))


class Counter(Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels):
        self._inc(_label_key(labels), n)

    def _set(self, key, v):  # counters are monotonic by contract
        raise TypeError(f"counter {self.name} cannot be set()")


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._fns: Dict[tuple, Callable[[], Optional[float]]] = {}

    def set(self, v: float, **labels):
        self._set(_label_key(labels), v)

    def set_max(self, v: float, **labels):
        """Watermark semantics: keep the max ever observed."""
        key = _label_key(labels)
        with self._lock:
            if float(v) > self._vals.get(key, float("-inf")):
                self._vals[key] = float(v)

    def set_fn(self, fn: Callable[[], Optional[float]], **labels):
        """Pull gauge: ``fn`` is evaluated at collection time
        (guarded — a dead producer yields no sample, never an
        exposition failure). The jit-cache-size gauge pattern."""
        with self._lock:
            self._fns[_label_key(labels)] = fn

    def series(self) -> List[Tuple[tuple, float]]:
        with self._lock:
            fns = list(self._fns.items())
        for key, fn in fns:
            try:
                v = fn()
            except Exception:
                v = None
            if v is not None:
                self._set(key, float(v))
            else:
                # a dead producer (weakref gone, feature absent)
                # must STOP exporting, not freeze its last sample —
                # the fn stays registered so a transient None (e.g.
                # a jit cache not yet built) can resume later
                with self._lock:
                    self._vals.pop(key, None)
        return super().series()


class Histogram(Metric):
    """Labelled histogram whose rows ARE ``LatencyHistogram``
    objects. ``row(**labels)`` hands the shared row out — the
    ``HistogramSet`` views of the supervisor/serve layers store the
    SAME objects, so the registry and the snapshot blocks can never
    disagree (parity by construction, not by double bookkeeping)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._rows: Dict[tuple, LatencyHistogram] = {}

    def row(self, **labels) -> LatencyHistogram:
        key = _label_key(labels)
        h = self._rows.get(key)
        if h is None:
            with self._lock:
                h = self._rows.setdefault(key, LatencyHistogram())
        return h

    def observe(self, seconds: float, **labels):
        self.row(**labels).record(seconds)

    def rows(self) -> List[Tuple[tuple, LatencyHistogram]]:
        with self._lock:
            return sorted(self._rows.items())

    def series(self) -> List[Tuple[tuple, float]]:
        return [(key, float(h.count)) for key, h in self.rows()]

    def matching(self, labels: dict) -> List[LatencyHistogram]:
        """Rows whose label set CONTAINS ``labels`` (the SLO
        watchdog's selector: sum e2e buckets across classes/pools
        for one kind)."""
        want = set(_label_key(labels))
        return [h for key, h in self.rows() if want <= set(key)]


class MetricRegistry:
    """Name -> typed metric, get-or-create with type checking."""

    _KINDS = {"counter": Counter, "gauge": Gauge,
              "histogram": Histogram}

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = locks.make_plane_lock("obs.registry")

    def _get(self, cls, name: str, help: str) -> Metric:
        name = _NAME_BAD.sub("_", name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{m.kind}, requested {cls.kind}")
            elif help and not m.help:
                m.help = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Metric]:
        with self._lock:
            return sorted(self._metrics.values(),
                          key=lambda m: m.name)

    # -- convenience reads (tests, SLO, stats views) -------------------

    def value(self, name: str, **labels) -> float:
        m = self.get(name)
        return 0.0 if m is None else m.value(**labels)

    def total(self, name: str) -> float:
        m = self.get(name)
        return 0.0 if m is None else m.total()

    # -- exposition ----------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4. Histograms emit
        cumulative ``_bucket{le=...}`` rows at the log2 upper edges
        (seconds), plus ``_sum``/``_count`` — rebuildable into any
        quantile with the one-octave bound of ``obs.hist``."""
        lines: List[str] = []
        for m in self.collect():
            if m.help:
                h = m.help.replace("\\", r"\\").replace("\n", r"\n")
                lines.append(f"# HELP {m.name} {h}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for key, row in m.rows():
                    snap_counts, count, sum_s = _hist_state(row)
                    acc = 0
                    for k in sorted(snap_counts):
                        acc += snap_counts[k]
                        le = (1 << k) / 1e6 if k else 1e-6
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(key, [('le', repr(le))])}"
                            f" {acc}")
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(key, [('le', '+Inf')])}"
                        f" {count}")
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} "
                                 f"{repr(float(sum_s))}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} "
                                 f"{count}")
            else:
                for key, v in m.series():
                    lines.append(
                        f"{m.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Compact JSON-able registry view (the daemon's inline
        ``stats`` answer and the dryrun's metrics block): per metric
        the type and either the labelled series (counter/gauge) or
        count/p99 per row (histogram)."""
        out: dict = {}
        for m in self.collect():
            if isinstance(m, Histogram):
                rows = {}
                for key, h in m.rows():
                    s = h.snapshot()
                    rows["/".join(v for _, v in key) or "_"] = {
                        "count": s.get("count", 0),
                        "p99_ms": s.get("p99_ms"),
                    }
                out[m.name] = {"type": m.kind, "rows": rows}
            else:
                out[m.name] = {"type": m.kind, "series": {
                    "/".join(v for _, v in key) or "_": v
                    for key, v in m.series()}}
        return out


def _hist_state(row: LatencyHistogram):
    with row._lock:
        return dict(row.counts), row.count, row.sum_s


# ------------------------------------------------------------------
# the process-global registry
# ------------------------------------------------------------------

_REG: Optional[MetricRegistry] = None
_REG_LOCK = locks.make_plane_lock("obs.registry_global")


def get_registry() -> MetricRegistry:
    global _REG
    if _REG is None:
        with _REG_LOCK:
            if _REG is None:
                _REG = MetricRegistry()
    return _REG


def counter(name: str, help: str = "") -> Counter:
    return get_registry().counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return get_registry().gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return get_registry().histogram(name, help)


def render() -> str:
    return get_registry().render()


def reset():
    """Swap in a fresh registry (tests: the ``obs.reset()``
    isolation contract — consumers built before the reset keep their
    old bound children; fresh consumers register fresh)."""
    global _REG
    with _REG_LOCK:
        _REG = MetricRegistry()


# ------------------------------------------------------------------
# device-memory watermark
# ------------------------------------------------------------------


def sample_device_memory() -> Optional[int]:
    """Sum of live accelerator buffer bytes, recorded into the
    ``pint_tpu_device_memory_watermark_bytes`` gauge (max-ever
    semantics). Returns the current total, or None off-accelerator.

    NEVER initializes a backend: it peeks jax's already-built client
    table only, because backend init hangs with no error on a wedged
    axon tunnel (CLAUDE.md gotchas) and a metrics scrape must not be
    able to wedge the process it is observing."""
    import sys

    try:
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is None or not getattr(xb, "_backends", None):
            return None
        import jax

        if jax.default_backend() == "cpu":
            return None
        total = 0
        for a in jax.live_arrays():
            try:
                if any(d.platform != "cpu" for d in a.devices()):
                    total += int(a.nbytes)
            except Exception:
                continue
        gauge("pint_tpu_device_memory_watermark_bytes",
              "peak live accelerator buffer bytes").set_max(total)
        return total
    except Exception:
        return None


# ------------------------------------------------------------------
# exposition server
# ------------------------------------------------------------------


def default_health() -> dict:
    """Breaker + pool states with NO engine lock: breaker snapshots
    hold only the per-breaker lock, the SLO status its ring lock."""
    out: dict = {"ok": True}
    try:
        from pint_tpu.runtime import supervisor as _sup

        brs = {b: br.snapshot()
               for b, br in dict(_sup._BREAKERS).items()}
        out["breakers"] = brs
        out["ok"] = not any(s.get("state") == "open"
                            for s in brs.values())
    except Exception as e:  # breakers unavailable != unhealthy
        out["breakers_error"] = repr(e)
    try:
        from pint_tpu.obs import slo as _slo

        w = _slo.get_watchdog()
        if w is not None:
            out["slo"] = w.status()
    except Exception:
        pass
    try:
        # ISSUE 14: the numerical-health verdict (worst recent
        # verdict per (pool, kind), last incident reason + age) —
        # monitor-lock only, never an engine lock; an armed monitor
        # with an unresolved incident degrades /healthz to 503 the
        # same way an open breaker does
        from pint_tpu.obs import health as _health

        h = _health.status()
        if h is not None:
            out["numerics"] = h
            if any(not v.get("ok", True)
                   for v in h.get("worst", {}).values()):
                out["ok"] = False
    except Exception:
        pass
    return out


class MetricsServer:
    """``/metrics`` + ``/healthz`` on a stdlib daemon thread.

    ``port=0`` binds an ephemeral port (tests); ``health_fn``
    overrides the default breaker-state payload (the daemon passes
    one that adds its engine's pool states — all lock-free reads).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricRegistry] = None,
                 health_fn: Optional[Callable[[], dict]] = None):
        import http.server

        reg = registry  # bound into the handler closure

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        sample_device_memory()
                        body = (reg or get_registry()).render() \
                            .encode("utf-8")
                        self._send(200, body,
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                    elif path == "/healthz":
                        h = (health_fn or default_health)()
                        body = json.dumps(h, default=str) \
                            .encode("utf-8")
                        self._send(200 if h.get("ok") else 503,
                                   body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # scrape must never kill us
                    try:
                        self._send(500, repr(e).encode(),
                                   "text/plain")
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name=f"pint-metrics-{self.port}")
            self._thread.start()
        return self

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread = None
