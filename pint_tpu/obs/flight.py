"""Flight recorder: dump the tracer's recent-span ring on serving
incidents.

A post-mortem of a production incident needs two things: *what was
pending* (the request journal already records that, fsynced) and
*what the system was doing* (nowhere, before this module). The
flight recorder pairs with the journal: when an incident trigger
fires — breaker-open, shed-burst, shutdown drain, unhandled engine
exception — the bounded ring of the most recent spans/events is
dumped to a timestamped JSON file in ``$PINT_TPU_FLIGHT_DIR``
(``config.flight_dir``), together with the trigger reason and any
caller-supplied context (supervisor counters, admission sheds).

Design constraints, in order:

- **never in the way**: a dump failure is counted, logged and
  swallowed — the incident path (a failover mid-drain) must not grow
  a new failure mode from its own black box;
- **rate-limited per reason**: a breaker flapping open every
  cooldown, or a sustained shed storm, writes one dump per
  ``min_interval_s`` (default 10 s) per reason, not one per event;
- **bounded**: the payload is the ring (``config.trace_ring_size``
  completed records) — dump size is O(ring), never O(history).

Arming the recorder (setting the dir) turns on span RECORDING even
when $PINT_TPU_TRACE is off: an empty black box records nothing.
The dump file is Chrome-trace-compatible at the ``events`` key
(same record shape the tracer exports), so a post-mortem can load
it in Perfetto after extracting ``{"traceEvents": events}``.
"""

from __future__ import annotations

import json
import os
from pint_tpu.runtime import locks
import time
from typing import Optional

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """One directory's incident dumper (module docstring)."""

    def __init__(self, dirpath: str, tracer,
                 min_interval_s: float = 10.0):
        self.dir = dirpath
        self.tracer = tracer
        self.min_interval_s = float(min_interval_s)
        self._last_by_reason: dict = {}
        self._lock = locks.make_lock("obs.flight")
        self.dumps = 0
        self.suppressed = 0
        self.errors = 0
        self.last_path: Optional[str] = None
        self.last_reason: Optional[str] = None

    def dump(self, reason: str, **extra) -> Optional[str]:
        """Write one incident dump; returns its path, or None when
        rate-limited or failed. Thread-safe; never raises."""
        now = time.monotonic()
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < self.min_interval_s:
                self.suppressed += 1
                return None
            self._last_by_reason[reason] = now
        try:
            os.makedirs(self.dir, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            fname = f"flight-{stamp}-{self.dumps:03d}-" \
                    f"{_slug(reason)}.json"
            path = os.path.join(self.dir, fname)
            doc = {
                "reason": reason,
                "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
                "pid": os.getpid(),
                "tracer": self.tracer.status(),
                "extra": _jsonable(extra),
                # the black box: most recent completed spans/events,
                # oldest first, Chrome-record shaped
                "events": self.tracer.records(),
            }
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                # default=str: one non-JSON span attr in the ring
                # must not kill the incident dump
                json.dump(doc, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except Exception as e:
            self.errors += 1
            try:
                from pint_tpu.logging import log

                log.warning("flight-recorder dump (%s) failed: %r",
                            reason, e)
            except Exception:
                pass
            return None
        with self._lock:
            self.dumps += 1
            self.last_path = path
            self.last_reason = reason
        try:
            from pint_tpu.logging import log

            log.warning("flight recorder dumped %d events to %s "
                        "(trigger: %s)", len(doc["events"]), path,
                        reason)
        except Exception:
            pass
        return path

    def status(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "dumps": self.dumps,
                    "suppressed": self.suppressed,
                    "errors": self.errors,
                    "last_reason": self.last_reason,
                    "last_path": self.last_path}


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in reason)[:48]


def _jsonable(obj):
    """Best-effort JSON coercion of caller-supplied context — a
    non-serializable extra must not kill the dump."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        return repr(obj)
