"""Performance-attribution plane: compile ledger, roofline
accounting, dispatch-wall decomposition arming, on-demand profiler
windows (ISSUE 15 tentpole).

The obs stack could say *what happened* (spans, ISSUE 10), *how
often* (metrics/SLO, ISSUE 11) and *whether the numbers are
trustworthy* (health, ISSUE 14) — but not *where the time goes*: the
roofline claim lived in one ad-hoc ``cost_analysis()`` call in
bench.py, compile walls were a single gauge with no history, and a
dispatch wall was one opaque number. This module is the organ that
attributes it:

- **compile ledger** (``CompileLedger``): every compile site the
  supervisor already detects — ``first_call`` per dispatch key,
  ``ExecutableCache`` serve classes, AOT restores, streaming/sampling
  chunk keys (all supervised dispatch keys) — reports
  ``(key, backend, compile_wall, flops, bytes_accessed, temp/peak
  bytes, when, aot_restored)`` through ``note_compile``. The ledger
  is registry-backed (``pint_tpu_perf_*``; snapshot is a derived
  view, parity test-asserted) and optionally JSONL-persisted
  (``$PINT_TPU_COMPILE_LEDGER``): a restarted worker reads the prior
  file back as ``prior`` entries, so a post-mortem knows exactly
  which executables existed and what each cost to build.
  ``cost_probe`` is THE one home of the
  ``lower().compile().cost_analysis()`` / ``memory_analysis()``
  pattern (graftlint G15) — it runs once per key (ledger dedup) and,
  because the probe re-pays most of the in-process compile,
  production call sites defer it to a background thread
  (``defer_cost=True``); it never lands on a hot path.

- **roofline accounting**: ``roofline``/``roofline_block`` derive
  achieved FLOP/s, bytes/s, arithmetic intensity and
  achieved-fraction against the per-backend ``PEAKS`` table from
  ledger cost ÷ a measured pure-step wall, and publish them as
  per-key gauges. bench.py's ad-hoc block is now a thin wrapper;
  bench artifacts embed the ledger-derived ``roofline`` block.

- **dispatch-wall decomposition arming**: ``enabled()`` is the one
  branch the supervisor consults before splitting a guarded
  dispatch's wall into queue_wait / host_assembly / device_wall /
  collect (``$PINT_TPU_PERF``; the timings themselves live in
  ``runtime/supervisor.py``, the histogram family in
  ``RuntimeMetrics.perf``). Disarmed, the supervisor pays one
  attribute read and a branch (the tracer-off discipline).

- **profiler windows** (``ProfilerWindows``): a supervised, bounded,
  rate-limited wrapper over ``jax.profiler`` traces. Armed by
  ``$PINT_TPU_PROFILE_DIR``; opened by ``request_window`` (the
  pint_serve ``{"kind": "profile"}`` inline answer) or
  ``auto_window`` (one-shot on ``slo_burn``/breaker-open, the
  flight-recorder pattern: capture the NEXT dispatches, one window
  per episode via the per-reason rate limit, never raises into the
  incident path). Every window writes a ``window.json`` metadata
  file cross-linking the triggering reason, flight-dump path and
  causal span ids, plus a Perfetto-loadable export of the span ring
  (``spans.json``); the device trace lands in the same directory.
  The stop is hang-proof (``stop_trace`` on a daemon thread under a
  join timeout — a wedged backend degrades the window to a labeled
  ``abandoned`` status, never a hung close). Windows add ZERO
  dispatches and zero per-dispatch cost: no dispatch path ever
  consults the profiler — the window is purely time-driven.

Everything host-side here is stdlib + the obs registry; jax is
imported only inside the probe/trace functions. ``obs.reset()``
drops the ledger, the profiler and the arming cache (the tracer
isolation contract).
"""

from __future__ import annotations

import json
import os
import threading

from pint_tpu.runtime import locks
import time
from typing import Optional

__all__ = ["CompileLedger", "ProfilerWindows", "PEAKS", "cost_probe",
           "get_ledger", "get_profiler", "note_compile",
           "roofline", "roofline_block", "roofline_from_latency",
           "ledger_summary", "request_window", "auto_window",
           "enabled", "configure", "reset", "status"]

# per-backend peak table for the achieved-fraction roofline framing
# (TPU v5e single-chip public peaks: 197 TFLOP/s bf16 MXU — f32
# matmul ~1/2 — and 819 GB/s HBM; bench.py's constants now read from
# here). Backends absent from the table get no achieved-fraction:
# fabricating a host "peak" would launder a latency-bound number
# into a fake utilization claim.
PEAKS = {
    "tpu": {"flops": 197e12, "bytes_per_s": 819e9},
}

# auto (incident-triggered) window length when the caller gives none
_AUTO_WINDOW_S = 5.0
# hang-proof bounds on trace control: start matters MORE than stop —
# the auto triggers run on incident paths (breaker trip = the backend
# just proved unresponsive), so an unbounded start_trace could wedge
# the very failover that fired it
_START_JOIN_S = 10.0
_STOP_JOIN_S = 30.0


def cost_probe(jitted, args) -> dict:
    """XLA's own static cost/memory analysis of a compiled program:
    ``{"flops", "bytes_accessed", "temp_bytes", "peak_bytes"}``
    (absent keys = the backend didn't report). THE one home of the
    ``lower().compile()`` probe pattern (graftlint G15); callers
    hand their jit object + example args/avals to ``note_compile``
    instead of probing ad hoc. Never raises; runs once per key by
    ledger dedup. NOTE the probe re-pays most of the in-process
    compile (the jit __call__ does not populate the lowering cache
    — measured ~70% of the first-call wall on XLA:CPU), which is
    why production call sites use ``defer_cost=True`` (background
    thread) and only bench probes synchronously."""
    out: dict = {}
    try:
        compiled = jitted.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        if ca:
            if ca.get("flops", 0) > 0:
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed", 0) > 0:
                out["bytes_accessed"] = float(ca["bytes accessed"])
        try:
            ma = compiled.memory_analysis()
            for field, name in (("temp_size_in_bytes", "temp_bytes"),
                                ("peak_memory_in_bytes",
                                 "peak_bytes")):
                v = getattr(ma, field, None)
                if v:
                    out[name] = int(v)
        except Exception:
            pass
    except Exception as e:
        try:
            from pint_tpu.logging import log

            log.debug("cost probe unavailable: %r", e)
        except Exception:
            pass
    return out


class CompileLedger:
    """Registry-backed, optionally JSONL-persisted compile ledger
    (module docstring). ``record`` merges by key — the compiles
    counter counts NEW keys only, so the registry counter and
    ``snapshot()['compiles']`` are the same number by construction
    (the ISSUE 11 parity discipline). Never raises: losing a ledger
    line must not fail the dispatch that just compiled."""

    def __init__(self, path: Optional[str] = None):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        self.path = config.compile_ledger_path() \
            if path is None else path
        self._lock = locks.make_lock("obs.perf_ledger")
        self._entries: dict = {}
        self._prior: dict = {}
        # counters are SCOPE-labelled per instance (the
        # RuntimeMetrics discipline): a configure() that swaps in a
        # fresh ledger mid-process must not inherit the old
        # instance's counts — each instance's registry series and
        # its snapshot stay the same number by construction
        self._scope = om.new_scope("ledger")
        self._c_compiles = om.counter(
            "pint_tpu_perf_compiles_total",
            "executables ledgered this process (new keys)"
        ).child(scope=self._scope)
        self._c_aot = om.counter(
            "pint_tpu_perf_aot_restored_total",
            "ledgered keys that came from an AOT restore"
        ).child(scope=self._scope)
        self._g_wall = om.gauge(
            "pint_tpu_perf_compile_wall_seconds",
            "ledgered first-call/compile wall per key")
        self._g_flops = om.gauge(
            "pint_tpu_perf_cost_flops",
            "XLA cost-analysis FLOPs per ledgered key")
        self._g_bytes = om.gauge(
            "pint_tpu_perf_cost_bytes",
            "XLA cost-analysis bytes accessed per ledgered key")
        if self.path:
            self._load_prior()

    # -- persistence ---------------------------------------------------

    def _load_prior(self):
        """Prior-process entries from the JSONL file: a restarted
        worker knows which executables existed before it (kept
        separate from this process's entries — `prior` in the
        snapshot — so the registry parity stays exact)."""
        try:
            with open(self.path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a crash
                    key = rec.pop("key", None)
                    if key:
                        self._prior[key] = rec
        except OSError:
            pass

    def _persist(self, key: str, entry: dict):
        if not self.path:
            return
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(dict(entry, key=key),
                                    sort_keys=True, default=str)
                         + "\n")
                fh.flush()
        except Exception:
            pass  # the ledger must never fail a dispatch

    # -- recording -----------------------------------------------------

    def record(self, key: str, backend: Optional[str] = None,
               compile_wall_s: Optional[float] = None,
               aot_restored: bool = False,
               kind: Optional[str] = None,
               jitted=None, args=None, defer_cost: bool = False,
               **cost) -> Optional[dict]:
        """Merge one compile observation into the ledger. With a
        ``jitted``+``args`` pair the XLA cost/memory probe runs —
        ONCE per key (a per-key in-flight marker under the lock
        dedups concurrent enrichers). ``defer_cost=True`` runs the
        probe on a BACKGROUND daemon thread: ``lower().compile()``
        re-pays most of the in-process compile (measured ~70% of the
        first-call wall on XLA:CPU; the jit __call__ does not
        populate the lowering cache), so production call sites
        (serve classes, streaming chunks) must never pay it on
        their dispatch path — bench, which reads the roofline
        immediately, probes synchronously. Returns the entry (a
        copy, in-flight markers stripped), or None on failure."""
        try:
            key = str(key)
            fields: dict = {}
            if backend is not None:
                fields["backend"] = str(backend)
            if kind is not None:
                fields["kind"] = str(kind)
            if compile_wall_s is not None:
                fields["compile_wall_s"] = round(
                    float(compile_wall_s), 6)
            for name in ("flops", "bytes_accessed", "temp_bytes",
                         "peak_bytes"):
                if cost.get(name) is not None:
                    fields[name] = float(cost[name])
            snap, new, need_probe = self._merge(
                key, fields, aot_restored,
                want_probe=jitted is not None)
            if need_probe:
                if defer_cost:
                    threading.Thread(
                        target=self._probe_and_merge,
                        args=(key, jitted, args), daemon=True,
                        name="pint-perf-cost").start()
                else:
                    self._probe_and_merge(key, jitted, args)
                    snap = self.get(key) or snap
            return snap
        except Exception:
            return None

    def _merge(self, key: str, fields: dict, aot_restored: bool,
               want_probe: bool):
        """Lock-disciplined entry merge: ALL entry mutation happens
        under ``self._lock`` (snapshot() copies under the same lock,
        so a scrape can never see a torn entry), gauges/counters/
        persistence run outside it from the copied view."""
        with self._lock:
            entry = self._entries.get(key)
            new = entry is None
            if new:
                entry = self._entries[key] = {
                    "when": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
                    "aot_restored": False,
                }
            changed = new or \
                any(entry.get(k) != v for k, v in fields.items()) \
                or (aot_restored and not entry["aot_restored"])
            entry.update(fields)
            if aot_restored:
                entry["aot_restored"] = True
            has_cost = "flops" in entry or "bytes_accessed" in entry
            need_probe = want_probe and not has_cost and \
                not entry.get("_probing")
            if need_probe:
                entry["_probing"] = True
            snap = {k: v for k, v in entry.items()
                    if not k.startswith("_")}
        self._publish_gauges(key, snap)
        if new:
            self._c_compiles.inc()
            if aot_restored:
                self._c_aot.inc()
        if changed:
            # merges persist too (the loader is last-wins per key):
            # an AOT-restored entry gains its first-call wall in a
            # LATER merge, and the JSONL post-mortem must carry it
            self._persist(key, snap)
        return snap, new, need_probe

    def _probe_and_merge(self, key: str, jitted, args):
        """The cost-probe half (possibly on a background thread):
        probe outside the lock, merge under it, then persist the
        enriched line (the JSONL loader is last-wins per key)."""
        try:
            probed = cost_probe(jitted, args or ())
        except Exception:
            probed = {}
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            entry.pop("_probing", None)
            entry.update(probed)
            snap = {k: v for k, v in entry.items()
                    if not k.startswith("_")}
        if probed:
            self._publish_gauges(key, snap)
            self._persist(key, snap)

    def _publish_gauges(self, key: str, snap: dict):
        if snap.get("compile_wall_s") is not None:
            self._g_wall.set(snap["compile_wall_s"], key=key)
        if snap.get("flops") is not None:
            self._g_flops.set(snap["flops"], key=key)
        if snap.get("bytes_accessed") is not None:
            self._g_bytes.set(snap["bytes_accessed"], key=key)

    # -- reads ---------------------------------------------------------

    def get(self, key: str) -> Optional[dict]:
        """This process's entry for ``key``, falling back to a prior
        run's persisted entry."""
        with self._lock:
            e = self._entries.get(str(key))
            if e is None:
                e = self._prior.get(str(key))
            return {k: v for k, v in e.items()
                    if not k.startswith("_")} \
                if e is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            entries = {k: {f: v for f, v in e.items()
                           if not f.startswith("_")}
                       for k, e in sorted(self._entries.items())}
            prior = len(self._prior)
        return {"compiles": len(entries),
                "aot_restored": sum(
                    1 for e in entries.values()
                    if e.get("aot_restored")),
                "total_compile_wall_s": round(sum(
                    e.get("compile_wall_s") or 0.0
                    for e in entries.values()), 4),
                "prior": prior,
                "path": self.path,
                "entries": entries}


# ------------------------------------------------------------------
# roofline accounting
# ------------------------------------------------------------------


def roofline(entry: dict, wall_s: float,
             backend: Optional[str] = None) -> Optional[dict]:
    """Roofline block for one ledger entry at a measured pure-step
    wall: achieved GFLOP/s + GB/s, arithmetic intensity (FLOP/byte),
    and — when the backend is in ``PEAKS`` — the achieved fraction
    of peak. None when the entry carries no cost."""
    if not entry or not wall_s or wall_s <= 0:
        return None
    flops = entry.get("flops")
    nbytes = entry.get("bytes_accessed")
    if not flops and not nbytes:
        return None
    out: dict = {"wall_ms": round(wall_s * 1e3, 3),
                 "source": "compile_ledger"}
    peak = PEAKS.get(backend or entry.get("backend") or "")
    if flops:
        out["flops"] = flops
        out["gflops_achieved"] = round(flops / wall_s / 1e9, 2)
        if peak:
            out["achieved_frac_flops"] = round(
                flops / wall_s / peak["flops"], 6)
    if nbytes:
        out["bytes"] = nbytes
        out["gbps_achieved"] = round(nbytes / wall_s / 1e9, 2)
        if peak:
            out["achieved_frac_hbm"] = round(
                nbytes / wall_s / peak["bytes_per_s"], 6)
    if flops and nbytes:
        out["arith_intensity"] = round(flops / nbytes, 4)
    return out


def roofline_block(key: str, wall_s: float,
                   backend: Optional[str] = None) -> Optional[dict]:
    """Ledger-derived roofline for one key (the bench artifact
    blocks), publishing the per-key achieved-FLOP/s and
    arithmetic-intensity gauges."""
    entry = get_ledger().get(key)
    block = roofline(entry or {}, wall_s, backend)
    if block is None:
        return None
    block["key"] = str(key)
    try:
        from pint_tpu.obs import metrics as om

        if block.get("gflops_achieved") is not None:
            om.gauge("pint_tpu_perf_achieved_gflops",
                     "achieved GFLOP/s per key (ledger cost / "
                     "measured pure-step wall)").set(
                block["gflops_achieved"], key=str(key))
        if block.get("arith_intensity") is not None:
            om.gauge("pint_tpu_perf_arith_intensity",
                     "arithmetic intensity (FLOP/byte) per key").set(
                block["arith_intensity"], key=str(key))
    except Exception:
        pass
    return block


def roofline_from_latency(latency_snapshot: Optional[dict],
                          backend: Optional[str] = None
                          ) -> Optional[dict]:
    """Per-key rooflines joined from a supervisor ``latency``
    snapshot ({"pool/key": {"dispatch_wall": {...}}}) and the
    ledger's cost entries — the serve/posterior artifact block.
    Output keys KEEP the pool prefix (a degraded run's device and
    host rows for one class must not collide), and host-pool rows
    are skipped entirely: the ledger cost describes the DEVICE
    executable, so scoring a pinned host wall against it (and the
    device backend's peak) would be exactly the laundered
    utilization claim the PEAKS table refuses. Walls use the exact
    ``mean_ms`` (sum/count), not the bucket-upper-edge p50. Keys
    with no ledgered cost (or no wall yet) are skipped."""
    led = get_ledger()
    out: dict = {}
    for row_key, metrics_ in (latency_snapshot or {}).items():
        pool, _, key = str(row_key).partition("/")
        if not key or pool.startswith("host"):
            continue
        dw = (metrics_ or {}).get("dispatch_wall") or {}
        wall_ms = dw.get("mean_ms") or dw.get("p50_ms")
        if not wall_ms:
            continue
        entry = led.get(key)
        if entry is None:
            continue
        block = roofline(entry, wall_ms / 1e3,
                         backend or entry.get("backend"))
        if block is not None:
            out[row_key] = block
    return out or None


def ledger_summary(max_keys: int = 64) -> dict:
    """Compact ``compiles`` artifact block: counts + per-key compile
    walls/costs (bounded — an artifact must stay a summary)."""
    snap = get_ledger().snapshot()
    keys = {}
    for k, e in list(snap["entries"].items())[:max_keys]:
        keys[k] = {f: e[f] for f in
                   ("backend", "compile_wall_s", "flops",
                    "bytes_accessed", "peak_bytes", "aot_restored")
                   if e.get(f) is not None}
    return {"compiles": snap["compiles"],
            "aot_restored": snap["aot_restored"],
            "total_compile_wall_s": snap["total_compile_wall_s"],
            "prior": snap["prior"],
            "keys": keys}


# ------------------------------------------------------------------
# on-demand profiler windows
# ------------------------------------------------------------------


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:48]


class ProfilerWindows:
    """Supervised, bounded, rate-limited ``jax.profiler`` windows
    (module docstring). One window open at a time; per-reason rate
    limit gives the one-window-per-episode contract for the auto
    (incident) triggers; disarmed (no dir) everything is a cheap
    labeled refusal and NOTHING is recorded."""

    def __init__(self, dirpath: Optional[str] = None,
                 max_s: Optional[float] = None,
                 min_interval_s: float = 60.0):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        self.dir = config.profile_dir() if dirpath is None \
            else dirpath
        self.max_s = config.profile_max_s() if max_s is None \
            else float(max_s)
        self.min_interval_s = float(min_interval_s)
        self._lock = locks.make_lock("obs.profiler")
        self._open: Optional[dict] = None
        self._last_by_reason: dict = {}
        self._n = 0
        self.last: Optional[dict] = None
        # scope-labelled per instance (the CompileLedger/
        # RuntimeMetrics discipline): a configure() that swaps in a
        # fresh profiler must not inherit the old instance's counts
        # in its own status()
        self._scope = om.new_scope("prof")
        self._c_windows = om.counter(
            "pint_tpu_perf_profile_windows_total",
            "profiler windows opened").child(scope=self._scope)
        self._c_suppressed = om.counter(
            "pint_tpu_perf_profile_suppressed_total",
            "profiler window requests refused (open/rate-limited)"
        ).child(scope=self._scope)
        self._c_errors = om.counter(
            "pint_tpu_perf_profile_errors_total",
            "profiler window start/stop failures"
        ).child(scope=self._scope)

    @property
    def armed(self) -> bool:
        return bool(self.dir)

    # -- the window lifecycle ------------------------------------------

    def request(self, seconds=None, reason: str = "manual",
                **extra) -> dict:
        """Open one bounded window capturing the NEXT dispatches.
        Never raises (the incident path calls this); returns a
        labeled status dict either way."""
        try:
            return self._request(seconds, reason, extra)
        except Exception as e:  # never into the caller's path
            try:
                self._c_errors.inc()
            except Exception:
                pass
            return {"ok": False, "reason": str(reason),
                    "error": f"{type(e).__name__}: {e}"}

    def _request(self, seconds, reason: str, extra: dict) -> dict:
        if not self.armed:
            return {"ok": False, "reason": reason,
                    "error": "profiler not armed "
                             "(set $PINT_TPU_PROFILE_DIR)"}
        try:
            seconds = float(seconds) if seconds else 0.0
        except (TypeError, ValueError):
            seconds = 0.0
        if not seconds > 0:
            seconds = min(_AUTO_WINDOW_S, self.max_s)
        seconds = min(seconds, self.max_s)
        now = time.monotonic()
        with self._lock:
            if self._open is not None:
                self._c_suppressed.inc()
                return {"ok": False, "reason": reason,
                        "error": "a profiler window is already open"}
            last = self._last_by_reason.get(reason)
            if last is not None and \
                    now - last < self.min_interval_s:
                self._c_suppressed.inc()
                return {"ok": False, "reason": reason,
                        "error": "rate-limited (one window per "
                                 f"{self.min_interval_s:.0f}s per "
                                 "reason)"}
            prev_stamp = last
            self._last_by_reason[reason] = now
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            wdir = os.path.join(
                self.dir, f"window-{stamp}-{self._n:03d}-"
                          f"{_slug(reason)}")
            self._n += 1
            meta = {"reason": reason, "seconds": seconds,
                    "dir": wdir, "status": "open",
                    "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime())}
            self._open = meta
        # causal cross-link: the triggering context's span ids and
        # any caller context (the flight-dump path on auto windows)
        try:
            from pint_tpu import obs

            ctx = obs.current()
            if ctx is not None:
                meta["trace"], meta["span"] = ctx
        except Exception:
            pass
        if extra:
            meta["extra"] = {k: v for k, v in extra.items()
                             if v is not None}
        # BOUNDED start, same discipline as the stop: the auto
        # triggers fire from incident paths (a breaker trip IS the
        # moment the backend just proved unresponsive), and
        # start_trace can touch the backend — it must never be able
        # to wedge the failover that called it. On a join timeout
        # the starter is abandoned and the window labeled; if the
        # orphaned start later completes, the NEXT window's start
        # fails with "already active" — labeled, never hung.
        start_done = threading.Event()
        start_err: list = []

        def starter():
            try:
                os.makedirs(wdir, exist_ok=True)
                import jax

                jax.profiler.start_trace(wdir)
            except Exception as e:
                start_err.append(e)
            finally:
                start_done.set()

        threading.Thread(target=starter, daemon=True,
                         name="pint-profile-start").start()
        started = start_done.wait(_START_JOIN_S) and not start_err
        if not started:
            if start_err:
                e = start_err[0]
                meta["status"] = "aborted"
                meta["error"] = f"{type(e).__name__}: {e}"
            else:
                meta["status"] = "start_timeout"
            self._c_errors.inc()
        self._write_meta(meta)
        try:
            from pint_tpu import obs

            obs.event("profile.window", reason=reason, dir=wdir,
                      status=meta["status"], seconds=seconds)
        except Exception:
            pass
        if not started:
            with self._lock:
                self._open = None
                self.last = meta
                # a window that never opened must not burn the
                # episode's rate-limit slot — the incident that
                # armed the feature still deserves its one trace
                if self._last_by_reason.get(reason) == now:
                    if prev_stamp is None:
                        self._last_by_reason.pop(reason, None)
                    else:
                        self._last_by_reason[reason] = prev_stamp
            return {"ok": False, "reason": reason, "dir": wdir,
                    "error": meta.get("error", meta["status"])}
        self._c_windows.inc()
        t = threading.Thread(target=self._close_after,
                             args=(meta, seconds), daemon=True,
                             name="pint-profile-window")
        t.start()
        return {"ok": True, "reason": reason, "dir": wdir,
                "seconds": seconds}

    def _close_after(self, meta: dict, seconds: float):
        time.sleep(seconds)
        self._stop(meta)

    def stop_open(self):
        """Force-close the open window now (tests, reset)."""
        with self._lock:
            meta = self._open
        if meta is not None:
            self._stop(meta)

    def _stop(self, meta: dict):
        # claim the window first: the deadline thread and a manual
        # stop must not both call stop_trace. The open slot is NOT
        # cleared until the final metadata lands — a poller that
        # sees ``open is None`` is guaranteed a terminal window.json
        with self._lock:
            if meta.get("_stopping") or self._open is not meta:
                return
            meta["_stopping"] = True
        done = threading.Event()

        def stopper():
            try:
                import jax

                jax.profiler.stop_trace()
                late = meta.get("status") == "abandoned"
                meta["status"] = "closed"
                if late:
                    # the join timed out (a big trace writing slowly
                    # is indistinguishable from a wedge at the time)
                    # but the stop DID finish — upgrade the labeled
                    # abandon to the eventual truth
                    self._write_meta(meta)
            except Exception as e:
                meta["status"] = "aborted"
                meta["error"] = f"{type(e).__name__}: {e}"
                self._c_errors.inc()
            finally:
                done.set()

        t = threading.Thread(target=stopper, daemon=True,
                             name="pint-profile-stop")
        t.start()
        if not done.wait(_STOP_JOIN_S):
            # hang-proof: a wedged backend cannot hold the window
            # open — the stopper thread is abandoned, the window is
            # labeled, the caller's drain proceeds
            meta["status"] = "abandoned"
            self._c_errors.inc()
        # Perfetto-loadable cross-link: the span ring covering the
        # window, causal ids intact (obs.export writes the Chrome
        # trace-event wrapper)
        try:
            from pint_tpu import obs

            if obs.recording():
                meta["spans"] = obs.export(
                    os.path.join(meta["dir"], "spans.json"))
        except Exception:
            pass
        self._write_meta(meta)
        with self._lock:
            if self._open is meta:
                self._open = None
            self.last = meta

    def _write_meta(self, meta: dict):
        try:
            os.makedirs(meta["dir"], exist_ok=True)
            path = os.path.join(meta["dir"], "window.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({k: v for k, v in meta.items()
                           if not k.startswith("_")},
                          fh, default=str, sort_keys=True)
                fh.flush()
            os.replace(tmp, path)
        except Exception:
            try:
                self._c_errors.inc()
            except Exception:
                pass

    def status(self) -> dict:
        with self._lock:
            open_ = self._open
            last = self.last
        return {"armed": self.armed, "dir": self.dir,
                "max_s": self.max_s,
                "windows": int(self._c_windows.value()),
                "suppressed": int(self._c_suppressed.value()),
                "errors": int(self._c_errors.value()),
                "open": {k: open_[k] for k in
                         ("reason", "dir", "seconds")}
                if open_ is not None else None,
                "last": {k: last[k] for k in
                         ("reason", "dir", "status")
                         if k in last}
                if last is not None else None}


# ------------------------------------------------------------------
# process-global plane (armed by env, like the tracer/monitor)
# ------------------------------------------------------------------

_LOCK = locks.make_lock("obs.perf_global")
_LEDGER: Optional[CompileLedger] = None
_PROFILER: Optional[ProfilerWindows] = None
_ENABLED: Optional[bool] = None


def get_ledger() -> CompileLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LOCK:
            if _LEDGER is None:
                _LEDGER = CompileLedger()
    return _LEDGER


def get_profiler() -> ProfilerWindows:
    global _PROFILER
    if _PROFILER is None:
        with _LOCK:
            if _PROFILER is None:
                _PROFILER = ProfilerWindows()
    return _PROFILER


def enabled() -> bool:
    """Is the dispatch-wall decomposition armed? ($PINT_TPU_PERF /
    ``configure(enabled=...)``.) The supervisor's one-branch gate —
    resolved once and cached until ``reset()``."""
    global _ENABLED
    e = _ENABLED
    if e is None:
        from pint_tpu import config

        with _LOCK:
            if _ENABLED is None:
                _ENABLED = config.perf_enabled()
            e = _ENABLED
    return e


def note_compile(key: str, backend: Optional[str] = None,
                 compile_wall_s: Optional[float] = None,
                 aot_restored: bool = False,
                 kind: Optional[str] = None,
                 jitted=None, args=None, defer_cost: bool = False,
                 **cost) -> Optional[dict]:
    """THE compile-site reporting surface (supervisor first_call,
    ExecutableCache classes, AOT restores, bench). Production call
    sites pass ``defer_cost=True`` so the probe's re-compile runs on
    a background thread, never on a dispatch path. Never raises."""
    try:
        return get_ledger().record(
            key, backend=backend, compile_wall_s=compile_wall_s,
            aot_restored=aot_restored, kind=kind, jitted=jitted,
            args=args, defer_cost=defer_cost, **cost)
    except Exception:
        return None


def request_window(seconds=None, reason: str = "manual",
                   **extra) -> dict:
    """Open one bounded profiler window (the pint_serve
    ``{"kind": "profile"}`` handler). Never raises."""
    try:
        return get_profiler().request(seconds, reason=reason,
                                      **extra)
    except Exception as e:
        return {"ok": False, "reason": str(reason),
                "error": f"{type(e).__name__}: {e}"}


def auto_window(reason: str, **extra) -> Optional[dict]:
    """Incident-triggered one-shot window (slo_burn, breaker-open):
    short default length, per-reason rate limit = one window per
    episode, disarmed = a cheap no-op, NEVER raises into the
    incident path that called it."""
    try:
        prof = _PROFILER
        if prof is None:
            from pint_tpu import config

            if not config.profile_dir():
                return None  # disarmed: don't even build the object
            prof = get_profiler()
        if not prof.armed:
            return None
        return prof.request(None, reason=reason, **extra)
    except Exception:
        return None


def configure(enabled: Optional[bool] = None, ledger_path=None,
              profile_dir=None, max_s: Optional[float] = None,
              min_interval_s: Optional[float] = None):
    """Explicitly (re)build the plane (tests, the bench overhead
    legs). Omitted arguments fall back to env/config; pass
    ``ledger_path=False`` / ``profile_dir=False`` to FORCE them off
    regardless of env (the bench off-leg needs a genuinely-off
    plane)."""
    global _LEDGER, _PROFILER, _ENABLED
    from pint_tpu import config

    prof = _PROFILER
    if prof is not None:
        prof.stop_open()  # outside the lock: the stop is bounded
    with _LOCK:
        if ledger_path is False:
            ledger_path = ""
        _LEDGER = CompileLedger(path=ledger_path)
        pdir = profile_dir
        if pdir is False:
            pdir = ""
        elif pdir is None:
            pdir = config.profile_dir()
        kw = {}
        if min_interval_s is not None:
            kw["min_interval_s"] = min_interval_s
        _PROFILER = ProfilerWindows(dirpath=pdir, max_s=max_s, **kw)
        _ENABLED = config.perf_enabled() if enabled is None \
            else bool(enabled)


def reset():
    """Drop the plane; the next use re-reads the env (called from
    ``obs.reset()`` — the isolation contract)."""
    global _LEDGER, _PROFILER, _ENABLED
    prof = _PROFILER
    if prof is not None:
        try:
            prof.stop_open()
        except Exception:
            pass
    with _LOCK:
        _LEDGER = None
        _PROFILER = None
        _ENABLED = None


def status() -> dict:
    """The ``perf`` status block: ledger counts + profiler state
    (cheap — no probe, no jax)."""
    out: dict = {"decomposition_armed": enabled()}
    led = _LEDGER
    if led is not None:
        snap = led.snapshot()
        out["compiles"] = snap["compiles"]
        out["ledger_path"] = snap["path"]
    prof = _PROFILER
    if prof is not None:
        out["profiler"] = prof.status()
    return out
