"""Structured telemetry for the serve/dispatch stack (ISSUE 10).

Three pieces, one process-global instance of each:

- ``obs.tracer`` — span tracing with causal ids (admission trace id
  -> queue/seal/route/dispatch/ack child spans; supervisor retry/
  timeout/breaker/failover/drift children), ring-buffered, Chrome
  trace-event export, JSONL stream mode (module: ``obs.tracer``);
- ``obs.hist`` — log-bucketed latency histograms (p50/p90/p99/max,
  power-of-two buckets, no per-sample storage);
- ``obs.flight`` — the flight recorder: the span ring dumped to
  ``$PINT_TPU_FLIGHT_DIR`` on breaker-open / shed-burst / shutdown
  drain / engine exception.

The module-level helpers below are THE instrumentation surface the
rest of the tree uses — ``span()``/``event()`` check one bool before
allocating anything, so with tracing off ($PINT_TPU_TRACE unset, no
stream, no flight dir) every instrumentation point costs an
attribute read and a branch (the <1% north-star contract, measured
in bench.py's ``obs`` block).

Configuration is lazy: the first use reads ``config.trace_enabled``
/ ``trace_stream_path`` / ``flight_dir`` / ``trace_ring_size``;
``configure()`` overrides explicitly (the daemon's CLI flags, tests)
and ``reset()`` drops back to env-driven state. Everything here is
pure stdlib — importable without jax, usable from the breaker and
journal layers that keep the same property.
"""

from __future__ import annotations

from pint_tpu.runtime import locks
from typing import Optional

from pint_tpu.obs import health  # noqa: F401  (ISSUE 14 monitor)
from pint_tpu.obs import metrics  # noqa: F401  (ISSUE 11 registry)
from pint_tpu.obs import perf  # noqa: F401  (ISSUE 15 perf plane)
from pint_tpu.obs.flight import FlightRecorder  # noqa: F401
from pint_tpu.obs.hist import HistogramSet, LatencyHistogram  # noqa: F401
from pint_tpu.obs.tracer import (  # noqa: F401
    NOOP_SPAN,
    SpanHandle,
    Tracer,
    attach,
    current,
)

__all__ = ["Tracer", "SpanHandle", "LatencyHistogram",
           "HistogramSet", "FlightRecorder", "metrics", "health",
           "perf", "get_tracer",
           "get_flight", "configure", "reset", "span", "open_span",
           "open_root", "event", "record_span", "current", "attach",
           "flight_dump", "status", "export"]

_LOCK = locks.make_lock("obs.global")
_TRACER: Optional[Tracer] = None
_FLIGHT: Optional[FlightRecorder] = None
_CONFIGURED = False


def _ensure():
    """Build the global tracer/flight pair from config on first use
    (or return the explicitly configured ones)."""
    global _TRACER, _FLIGHT, _CONFIGURED
    if _TRACER is not None:
        return
    with _LOCK:
        if _TRACER is not None:
            return
        from pint_tpu import config

        fdir = config.flight_dir()
        # an armed flight recorder needs a populated ring even when
        # trace export is off — recording is cheap, an empty black
        # box is useless
        tracer = Tracer(ring_size=config.trace_ring_size(),
                        recording=config.trace_enabled()
                        or fdir is not None,
                        stream=config.trace_stream_path())
        _TRACER = tracer
        _FLIGHT = FlightRecorder(fdir, tracer) if fdir else None
        _CONFIGURED = False


def get_tracer() -> Tracer:
    _ensure()
    return _TRACER


def get_flight() -> Optional[FlightRecorder]:
    _ensure()
    return _FLIGHT


def configure(enabled: Optional[bool] = None,
              stream=None, flight_dir=None,
              ring_size: Optional[int] = None) -> Tracer:
    """Explicitly (re)build the global tracer/flight pair — the
    daemon's CLI flags and tests. Omitted (None) arguments fall back
    to the env/config defaults; pass ``stream=False`` /
    ``flight_dir=False`` to FORCE them off regardless of env (the
    bench overhead measurement needs a genuinely-off tracer even in
    a deployment with a stream or flight recorder armed)."""
    global _TRACER, _FLIGHT, _CONFIGURED
    from pint_tpu import config

    with _LOCK:
        if _TRACER is not None:
            _TRACER.close()
        if flight_dir is None:
            flight_dir = config.flight_dir()
        elif flight_dir is False:
            flight_dir = None
        if stream is None:
            stream = config.trace_stream_path()
        elif stream is False:
            stream = None
        recording = config.trace_enabled() if enabled is None \
            else bool(enabled)
        tracer = Tracer(
            ring_size=config.trace_ring_size()
            if ring_size is None else ring_size,
            recording=recording or flight_dir is not None
            or stream is not None,
            stream=stream)
        _TRACER = tracer
        _FLIGHT = FlightRecorder(flight_dir, tracer) \
            if flight_dir else None
        _CONFIGURED = True
        return tracer


def reset():
    """Drop the global instances; the next use re-reads the env
    (tests: a configured tracer must never leak across tests). Also
    swaps in a fresh metric registry and stops the SLO watchdog
    (ISSUE 11) — the same isolation contract: consumers built before
    the reset keep their old bound children, fresh consumers
    register fresh."""
    global _TRACER, _FLIGHT, _CONFIGURED
    with _LOCK:
        if _TRACER is not None:
            _TRACER.close()
        _TRACER = None
        _FLIGHT = None
        _CONFIGURED = False
    from pint_tpu.obs import slo

    slo.reset()
    metrics.reset()
    # ISSUE 14: the health monitor holds bound registry children and
    # env-derived thresholds — same staleness hazard as the tracer
    health.reset()
    # ISSUE 15: the perf plane (compile ledger, profiler windows,
    # decomposition arming cache) and the global profiling
    # scoreboard's registry-shared rows — both hold bound children
    # of the registry that was just swapped
    perf.reset()
    try:
        from pint_tpu import profiling

        profiling.scoreboard.reset()
    except Exception:
        pass
    # ISSUE 18: the lock-order graph + per-edge incident latches +
    # arming cache — the same episode/isolation contract as the
    # numerics incident latches above
    from pint_tpu.runtime import locks as _locks

    _locks.reset()


# ------------------------------------------------------------------
# the instrumentation surface (hot-path cheap when off)
# ------------------------------------------------------------------


def span(name: str, parent=None, trace=None, **attrs):
    """Context-managed span under the current context (see
    ``Tracer.span``); the shared no-op when tracing is off."""
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    if not t.recording:
        return NOOP_SPAN
    return t.span(name, parent=parent, trace=trace, **attrs)


def open_span(name: str, parent=None, trace=None, **attrs):
    """Open a held span (ends explicitly; see ``Tracer.open_span``)."""
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    if not t.recording:
        return NOOP_SPAN
    return t.open_span(name, parent=parent, trace=trace, **attrs)


def open_root(name: str, label: str = "t", **attrs):
    """Open a ROOT span of a FRESH trace (the serve request root at
    admission, a device fit) — never parented under ambient context.
    """
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    if not t.recording:
        return NOOP_SPAN
    return t.open_span(name, trace=t.new_trace(label), **attrs)


def event(name: str, **attrs):
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    if t.recording:
        t.record_event(name, **attrs)


def record_span(name: str, t0_us: float, t1_us: float, parent=None,
                trace=None, **attrs):
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    if t.recording:
        t.record_span(name, t0_us, t1_us, parent=parent, trace=trace,
                      **attrs)


def recording() -> bool:
    t = _TRACER
    if t is None:
        _ensure()
        t = _TRACER
    return t.recording


def flight_dump(reason: str, **extra) -> Optional[str]:
    """Trigger a flight-recorder dump (no-op when no flight dir is
    armed). Never raises — incident paths call this."""
    f = get_flight()
    if f is None:
        return None
    return f.dump(reason, **extra)


def export(path: str) -> int:
    """Export the global tracer's ring as Chrome trace-event JSON."""
    return get_tracer().export(path)


def status() -> dict:
    """The ``obs`` block every artifact/snapshot embeds: tracer
    state + flight-recorder state."""
    t = get_tracer()
    out = {"trace": t.status()}
    f = get_flight()
    out["flight"] = f.status() if f is not None else None
    # ISSUE 15: the perf plane's cheap status (ledger counts +
    # profiler window state) — additive, no probe, no jax
    try:
        out["perf"] = perf.status()
    except Exception:
        pass
    return out
