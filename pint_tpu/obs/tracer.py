"""Span tracer: causal IDs, ring buffer, Chrome trace-event export.

SURVEY.md §5 names tracing a first-class requirement the reference
never had (loguru DEBUG lines in src/pint/toa.py / fitter.py are its
only visibility); after the async/pipelined/breaker/admission layers
of ISSUEs 4-9 flat counters can say *that* a request degraded but
never *what sequence of events led there*. This tracer makes a
DEGRADED artifact a replayable causal story:

- **spans** carry a trace id (assigned at serve admission, or fresh
  per fit), a span id, and a parent span id — parent/child links are
  explicit, so an exported trace can be walked bottom-up from any
  terminal span to the admission that caused it;
- **context propagation** rides a ``contextvars.ContextVar``: a span
  opened inside another's ``with`` block parents automatically, and
  ``attach(ctx)`` re-enters a captured context on another thread
  (the supervisor's async workers, the serve drain loop);
- **ring buffer**: completed records land in a bounded ring
  (``config.trace_ring_size``) under one short lock — a long-lived
  serving process never grows, and the ring IS the flight-recorder
  payload (``pint_tpu.obs.flight``);
- **export** (``Tracer.export``) writes Chrome trace-event JSON
  ({"traceEvents": [...]}, "X" complete events + "i" instants) that
  loads in Perfetto / chrome://tracing; span/parent/trace ids ride
  the ``args`` of every event so causality survives the format;
- **stream mode**: with a JSONL stream attached every completed
  record is ALSO appended (one JSON object per line, flushed) as it
  completes — the ``pint_serve`` live-tail, crash-safe where the
  in-memory ring is not;
- **off by default**: ``recording`` is False unless $PINT_TPU_TRACE
  / a stream / an armed flight recorder turns it on, and the
  module-level ``span()``/``event()`` helpers return a shared no-op
  before allocating anything — the fault-free hot path pays one
  attribute read and a branch per instrumentation point (measured
  <1% on the north-star fit, bench.py ``obs`` block).

Timestamps are ``time.monotonic()`` microseconds against the
tracer's epoch — the same clock the serve layer stamps
``admitted_at`` with, so retroactive spans (queue-wait, recorded at
dispatch time from the admission stamp) land on the same axis.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading

from pint_tpu.runtime import locks
import time
from typing import Optional

__all__ = ["Tracer", "SpanHandle", "current", "attach"]

# the active span context: (trace_id, span_id) of the innermost open
# span on this thread/task, or None outside any span
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "pint_tpu_obs_span", default=None)


def current():
    """(trace_id, span_id) of the innermost open span in this
    context, or None. Capture it on the issuing thread and re-enter
    with ``attach`` on a worker thread."""
    return _CURRENT.get()


class attach:
    """Re-enter a captured span context on another thread: spans
    opened inside the ``with`` block parent under ``ctx`` exactly as
    if they were opened where it was captured."""

    __slots__ = ("ctx", "_token")

    def __init__(self, ctx):
        self.ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _CURRENT.set(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        _CURRENT.reset(self._token)
        return False


class SpanHandle:
    """One OPEN span. ``event()`` attaches instants under it,
    ``end()`` records the completed span into the ring. Usable as a
    context manager (``Tracer.span``) or held open across callbacks
    (the serve request root span ends at terminal resolution)."""

    __slots__ = ("tracer", "name", "trace_id", "span_id",
                 "parent_id", "t0", "attrs", "_ended", "_token")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 t0, attrs):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs
        self._ended = False
        self._token = None

    @property
    def ctx(self):
        return (self.trace_id, self.span_id)

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def event(self, name, **attrs):
        """Instant event parented under this span."""
        self.tracer.record_event(name, trace_id=self.trace_id,
                                 parent_id=self.span_id, **attrs)
        return self

    def end(self, status: Optional[str] = None, **attrs):
        if self._ended:
            return
        self._ended = True
        if status is not None:
            self.attrs["status"] = status
        self.attrs.update(attrs)
        self.tracer._record(self.name, "X", self.t0,
                            self.tracer._now() - self.t0,
                            self.trace_id, self.span_id,
                            self.parent_id, self.attrs)

    # -- context-manager form ------------------------------------------

    def __enter__(self):
        self._token = _CURRENT.set(self.ctx)
        return self

    def __exit__(self, etype, exc, tb):
        _CURRENT.reset(self._token)
        if etype is not None and "status" not in self.attrs:
            self.attrs["status"] = "error"
            self.attrs["error"] = f"{etype.__name__}: {exc}"
        self.end()
        return False


class _NoopSpan:
    """Shared do-nothing stand-in returned when the tracer is off:
    no allocation, no lock, usable everywhere a SpanHandle is."""

    __slots__ = ()
    ctx = None
    trace_id = None
    span_id = None

    def set(self, **kw):
        return self

    def event(self, name, **kw):
        return self

    def end(self, status=None, **kw):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Ring-buffered span recorder (module docstring).

    ``recording`` gates everything: False means every entry point
    returns the shared no-op immediately. The ring holds completed
    records as plain dicts already shaped like Chrome trace events
    (``ph`` "X" complete / "i" instant, ``ts``/``dur`` in
    microseconds against the tracer epoch, causal ids in ``args``).
    """

    def __init__(self, ring_size: int = 16384, recording: bool = False,
                 stream=None):
        self.recording = bool(recording)
        self.ring_size = max(16, int(ring_size))
        self._ring: list = []
        self._head = 0            # next slot once the ring is full
        self._lock = locks.make_lock("obs.tracer.ring")
        self._ids = 0
        self._traces = 0
        self.dropped = 0          # records overwritten by the ring
        self.epoch = time.monotonic()
        self._pid = os.getpid()
        # stream: a writable text file object, or a path to open in
        # append mode; each completed record is one flushed JSON
        # line. Its OWN lock: a slow stream (NFS, full pipe) must
        # serialize only other stream writers, never the ring
        # appends the admission/dispatch hot paths perform under
        # self._lock
        self._stream = None
        self._stream_lock = locks.make_lock("obs.tracer.stream")
        self._stream_path = None
        if stream is not None:
            if isinstance(stream, str):
                self._stream_path = stream
                d = os.path.dirname(os.path.abspath(stream))
                if d:
                    os.makedirs(d, exist_ok=True)
                self._stream = open(stream, "a", encoding="utf-8")
            else:
                self._stream = stream
            self.recording = True

    # -- clock / ids ---------------------------------------------------

    def _now(self) -> float:
        """Microseconds since the tracer epoch."""
        return (time.monotonic() - self.epoch) * 1e6

    def monotonic_us(self, t_monotonic: float) -> float:
        """Map a raw time.monotonic() stamp onto the tracer's axis
        (retroactive spans: serve queue-wait from admitted_at)."""
        return (t_monotonic - self.epoch) * 1e6

    def _next_id(self) -> int:
        with self._lock:
            self._ids += 1
            return self._ids

    def new_trace(self, label: str = "t") -> str:
        """Fresh trace id (a serve request at admission, a device
        fit, a dispatch with no enclosing context)."""
        with self._lock:
            self._traces += 1
            return f"{label}{self._traces}"

    # -- span API ------------------------------------------------------

    def open_span(self, name: str, parent=None, trace: Optional[str] = None,
                  **attrs) -> SpanHandle:
        """Open a span WITHOUT entering its context (held across
        threads/callbacks; ``end()`` records it). ``parent`` defaults
        to the current context; an explicit ``trace=`` forces a ROOT
        span of that trace (the serve admission root, a fresh fit)
        regardless of ambient context."""
        if not self.recording:
            return NOOP_SPAN
        if parent is None and trace is None:
            parent = _CURRENT.get()
        if parent is not None:
            trace_id, parent_id = parent
        else:
            trace_id, parent_id = trace or self.new_trace(), None
        return SpanHandle(self, name, trace_id, self._next_id(),
                          parent_id, self._now(), attrs)

    def span(self, name: str, parent=None, trace=None, **attrs):
        """Context-managed span: enters the context (children parent
        automatically) and records on exit."""
        if not self.recording:
            return NOOP_SPAN
        return self.open_span(name, parent=parent, trace=trace,
                              **attrs)

    def record_event(self, name: str, trace_id=None, parent_id=None,
                     **attrs):
        """Instant event. With no explicit parent it attaches under
        the current context (or a fresh root trace)."""
        if not self.recording:
            return
        if trace_id is None:
            ctx = _CURRENT.get()
            if ctx is not None:
                trace_id, parent_id = ctx
            else:
                trace_id = self.new_trace()
        self._record(name, "i", self._now(), None, trace_id,
                     self._next_id(), parent_id, attrs)

    def record_span(self, name: str, t0_us: float, t1_us: float,
                    parent=None, trace=None, **attrs):
        """Retroactive complete span from two timestamps already on
        the tracer axis (``monotonic_us``) — how queue-wait spans are
        recorded at dispatch time from the admission stamp."""
        if not self.recording:
            return
        if parent is not None:
            trace_id, parent_id = parent
        else:
            trace_id, parent_id = trace or self.new_trace(), None
        self._record(name, "X", t0_us, max(0.0, t1_us - t0_us),
                     trace_id, self._next_id(), parent_id, attrs)

    # -- ring + stream -------------------------------------------------

    def _record(self, name, ph, ts, dur, trace_id, span_id,
                parent_id, attrs):
        rec = {"name": name, "ph": ph, "ts": round(ts, 1),
               "pid": self._pid,
               "tid": threading.get_ident() & 0x7FFFFFFF,
               "args": dict(attrs, trace=trace_id, span=span_id)}
        if parent_id is not None:
            rec["args"]["parent"] = parent_id
        if ph == "X":
            rec["dur"] = round(dur, 1)
        if ph == "i":
            rec["s"] = "t"  # instant scope: thread
        with self._lock:
            if len(self._ring) < self.ring_size:
                self._ring.append(rec)
            else:
                self._ring[self._head] = rec
                self._head = (self._head + 1) % self.ring_size
                self.dropped += 1
            stream = self._stream
        if stream is not None:
            try:
                # default=str: an instrumentation site passing a
                # non-JSON attr (a numpy scalar, a rid object) must
                # degrade to its string form, never raise out of the
                # dispatch/serve path it was merely tracing
                line = json.dumps(rec, default=str)
                with self._stream_lock:
                    stream.write(line + "\n")
                    stream.flush()
            except (OSError, ValueError, TypeError):
                pass  # a dead stream must never fail a dispatch

    def records(self) -> list:
        """Ring contents, oldest first (a copy)."""
        with self._lock:
            return self._ring[self._head:] + self._ring[:self._head]

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring = []
            self._head = 0
            self.dropped = 0

    def close(self):
        if self._stream is not None and self._stream_path is not None:
            try:
                self._stream.close()
            except OSError:
                pass
            self._stream = None

    # -- export --------------------------------------------------------

    def export(self, path: str) -> int:
        """Write the ring as Chrome trace-event JSON (the
        {"traceEvents": [...]} wrapper Perfetto / chrome://tracing
        parse). Returns the number of events written. Atomic
        (tmp + rename) so a reader never sees a torn file."""
        events = sorted(self.records(), key=lambda r: r["ts"])
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"tracer": "pint_tpu.obs",
                             "dropped": self.dropped}}
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            # default=str: one non-JSON attr must not kill the whole
            # export (same contract as the stream writer above)
            json.dump(doc, fh, default=str)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return len(events)

    def status(self) -> dict:
        with self._lock:
            n = len(self._ring)
        return {"recording": self.recording, "events": n,
                "dropped": self.dropped,
                "ring_size": self.ring_size,
                "spans_started": self._ids,
                "stream": self._stream_path}
