"""Log-bucketed latency histograms: p50/p90/p99/max with no
per-sample storage.

The serve layer judged latency from a capped per-bucket reservoir
(sort + nearest-rank at snapshot time) and the router from EWMA
rates — fine for means, blind in the tail and unbounded-ish in
memory. Here every sample lands in a FIXED power-of-two bucket:
bucket ``k`` covers [2^(k-1), 2^k) microseconds, so ~41 buckets span
1 us to ~20 minutes, memory is O(1) per (pool, kind, class, metric)
row regardless of traffic, and recording is an integer bit_length +
one dict bump under a short lock. Quantiles are read by cumulative
walk and reported at the bucket's UPPER edge — a conservative bound
with at most one-octave (2x) resolution error, which is the right
trade for judging SLO tails ("p99 is under 8 ms" is actionable;
"p99 is 6.1 vs 6.3 ms" never is).

``HistogramSet`` is the keyed table the serve scheduler feeds per
(pool, kind, shape-class) x metric (queue_wait / dispatch_wall /
e2e), embedded as the ``latency`` block of ``ServeMetrics.snapshot``
and the bench artifacts; the dispatch supervisor keeps a per-key set
for non-serve callers (device fits, PTA solves).
"""

from __future__ import annotations

from pint_tpu.runtime import locks
from typing import Dict, Optional, Tuple

__all__ = ["LatencyHistogram", "HistogramSet"]

# bucket k covers [2^(k-1), 2^k) us; k=0 is the sub-microsecond bin.
# 41 buckets reach 2^40 us ~ 12.7 days — nothing a serving process
# measures can overflow it, and overflow clamps to the top bucket.
_MAX_BUCKET = 41


def _bucket_of(us: float) -> int:
    # not (us >= 1.0) also catches NaN; the top-bucket clamp catches
    # inf BEFORE int() (int(inf) raises OverflowError — a garbage
    # sample must clamp, never crash the recording thread)
    if not (us >= 1.0):
        return 0
    if us >= float(1 << _MAX_BUCKET):
        return _MAX_BUCKET
    return min(_MAX_BUCKET, int(us).bit_length())


def _upper_edge_ms(k: int) -> float:
    """Upper edge of bucket k in milliseconds."""
    return (1 << k) / 1e3 if k else 1e-3


class LatencyHistogram:
    """One metric's fixed-bucket histogram. ``record`` takes seconds
    (the unit every wall in this repo is measured in)."""

    __slots__ = ("counts", "count", "sum_s", "max_s", "_lock")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0
        self._lock = locks.make_plane_lock("obs.hist_row")

    def record(self, seconds: float):
        if not (seconds >= 0.0):   # negative AND NaN clamp to zero
            seconds = 0.0
        k = _bucket_of(seconds * 1e6)
        with self._lock:
            self.counts[k] = self.counts.get(k, 0) + 1
            self.count += 1
            self.sum_s += seconds
            if seconds > self.max_s:
                self.max_s = seconds

    def quantile_ms(self, q: float) -> Optional[float]:
        """Upper-edge quantile in ms (nearest-rank over buckets);
        None when empty. q in [0, 100]."""
        with self._lock:
            if not self.count:
                return None
            rank = max(1, int(round(q / 100.0 * self.count)))
            acc = 0
            for k in sorted(self.counts):
                acc += self.counts[k]
                if acc >= rank:
                    return _upper_edge_ms(k)
            return _upper_edge_ms(max(self.counts))

    def snapshot(self) -> dict:
        with self._lock:
            if not self.count:
                return {"count": 0}
            mean_ms = self.sum_s / self.count * 1e3
            buckets = {str(k): v
                       for k, v in sorted(self.counts.items())}
            count, max_s = self.count, self.max_s
        return {
            "count": count,
            "p50_ms": self.quantile_ms(50),
            "p90_ms": self.quantile_ms(90),
            "p99_ms": self.quantile_ms(99),
            "max_ms": round(max_s * 1e3, 3),
            "mean_ms": round(mean_ms, 3),
            # sparse log2 bucket table: key k counts samples in
            # [2^(k-1), 2^k) us — enough to rebuild any quantile
            "log2_us_buckets": buckets,
        }


class HistogramSet:
    """Keyed histogram table: one LatencyHistogram per
    (key..., metric) row, created on first record. Keys are joined
    with "/" in snapshots (the serve metrics key convention).

    ``row_factory(key, metric)`` (ISSUE 11) lets the row objects be
    SHARED with a registry histogram (``obs.metrics.Histogram.row``)
    — both views then read the same LatencyHistogram, so the
    snapshot block and the /metrics exposition can never disagree."""

    def __init__(self, row_factory=None):
        self._rows: Dict[Tuple, LatencyHistogram] = {}
        self._lock = locks.make_plane_lock("obs.hist_set")
        self._factory = row_factory or \
            (lambda key, metric: LatencyHistogram())

    def record(self, key: Tuple, metric: str, seconds: float):
        row = (tuple(key), metric)
        h = self._rows.get(row)
        if h is None:
            with self._lock:
                h = self._rows.get(row)
                if h is None:
                    h = self._rows[row] = self._factory(row[0],
                                                        metric)
        h.record(seconds)

    def get(self, key: Tuple, metric: str) -> Optional[LatencyHistogram]:
        return self._rows.get((tuple(key), metric))

    def __len__(self):
        return len(self._rows)

    def snapshot(self) -> dict:
        """{key-string: {metric: histogram snapshot}}."""
        with self._lock:
            rows = dict(self._rows)
        out: dict = {}
        for (key, metric), h in sorted(rows.items(),
                                       key=lambda kv: (str(kv[0][0]),
                                                       kv[0][1])):
            ks = "/".join(str(x) for x in key)
            out.setdefault(ks, {})[metric] = h.snapshot()
        return out
