"""SLO burn-rate watchdog: declarative objectives over the metrics
registry, multi-window error-budget detection, flight-recorder fire
(ISSUE 11).

Nothing watched the counters over time before this module: a latency
regression or shed creep surfaced only when a breaker opened or a
human read a bench artifact. The watchdog closes that gap with the
classic SRE multi-window burn-rate recipe:

- a **self-sampling ring**: every ``$PINT_TPU_SLO_INTERVAL_S`` the
  watchdog snapshots each SLO's raw cumulative state (histogram
  bucket counts, counter totals, gauge values) into a bounded deque
  — windowed rates are DELTAS between ring samples, so the registry
  stays cumulative-only and the ring is O(slow_window / interval);
- **burn rate** = (error rate over a window) / (the error budget the
  objective leaves). An SLO fires only when the FAST window and the
  SLOW window both burn past the spec's threshold — a one-sample
  spike inflates the fast window but not the slow one, and a stale
  regression burns the slow window while the fast one has recovered;
  neither alone fires (the no-false-fire contract of the tests);
- on fire, the **flight recorder** dumps with reason
  ``slo_burn:<name>`` — the post-mortem black box is written while
  the regression is happening, BEFORE the breaker-open dump the
  failure may eventually escalate to. One fire per burn episode
  (latched until the fast window recovers; the recorder additionally
  rate-limits per reason).

Three SLI types (``type`` in a spec dict):

- ``latency``: good = samples at/under ``objective_ms`` in a
  registry histogram's delta buckets (upper-edge attribution — the
  same one-octave conservative bound as every quantile in
  ``obs.hist``); ``target`` is the good fraction (0.99 = "p99 under
  objective");
- ``ratio``: error rate = delta(``bad`` counters) /
  delta(``total`` counters) against an allowed ``budget`` (the
  shed-rate SLO);
- ``gauge``: error rate = fraction of window samples where the gauge
  exceeds ``objective`` against ``budget`` (the dispatch
  ``overhead_frac`` SLO — fed wherever a pure-step-vs-wall
  measurement exists, e.g. bench.py's dispatch-overhead block).

Off by default; ``$PINT_TPU_SLO`` arms it (truthy = the default spec
set; inline JSON or a JSON file path = custom specs). All env
parsing goes through validated ``config`` accessors per the
``dispatch_rtt_override_ms`` convention — a typo warns and is
ignored, never silently mis-arms a watchdog. Pure stdlib.
"""

from __future__ import annotations

import collections
import threading

from pint_tpu.runtime import locks
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pint_tpu.obs import metrics as om

__all__ = ["SLOSpec", "SLOWatchdog", "default_specs", "get_watchdog",
           "maybe_start", "status", "reset"]


@dataclass
class SLOSpec:
    name: str
    type: str                       # latency | ratio | gauge
    metric: str = ""                # latency/gauge source
    labels: Dict[str, str] = field(default_factory=dict)
    bad: List[str] = field(default_factory=list)    # ratio numerator
    total: List[str] = field(default_factory=list)  # ratio denom
    objective_ms: float = 1000.0    # latency threshold
    target: float = 0.99            # latency good-fraction objective
    objective: float = 0.1          # gauge threshold
    budget: float = 0.05            # ratio/gauge error budget
    fast_s: float = 60.0
    slow_s: float = 300.0
    burn: float = 2.0               # fire when BOTH windows >= this
    min_events: int = 4             # latency/ratio: delta floor
    min_samples: int = 2            # ring samples inside fast window

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        """Validated construction — raises ValueError on a spec that
        cannot be evaluated (config.slo_specs warns and drops it)."""
        if not isinstance(d, dict) or not d.get("name") \
                or d.get("type") not in ("latency", "ratio", "gauge"):
            raise ValueError(f"invalid SLO spec {d!r}")
        known = {f for f in cls.__dataclass_fields__}
        kw = {k: v for k, v in d.items() if k in known}
        spec = cls(**kw)
        if spec.type in ("latency", "gauge") and not spec.metric:
            raise ValueError(f"SLO {spec.name!r}: metric required")
        if spec.type == "ratio" and not (spec.bad and spec.total):
            raise ValueError(f"SLO {spec.name!r}: bad+total required")
        for fname in ("fast_s", "slow_s", "burn", "budget"):
            v = float(getattr(spec, fname))
            if not v > 0.0:
                raise ValueError(
                    f"SLO {spec.name!r}: {fname} must be > 0")
        if not 0.0 < float(spec.target) < 1.0:
            raise ValueError(f"SLO {spec.name!r}: target in (0,1)")
        return spec


def default_specs() -> List[SLOSpec]:
    """The armed-by-truthy-$PINT_TPU_SLO set: e2e p99 per serve kind,
    overall shed rate, dispatch overhead_frac."""
    specs = [
        SLOSpec(name=f"e2e_p99_{kind}", type="latency",
                metric="pint_tpu_serve_latency_seconds",
                labels={"metric": "e2e", "kind": kind},
                objective_ms=1000.0, target=0.99)
        for kind in ("gls", "phase", "posterior")
    ]
    specs.append(SLOSpec(
        name="shed_rate", type="ratio",
        bad=["pint_tpu_serve_shed_total"],
        # attempts, not submitted: quota/overload sheds never reach
        # the submitted counter, and a 100%-shed storm with a
        # flat denominator would evaluate to None instead of firing
        total=["pint_tpu_serve_attempts_total"],
        budget=0.05))
    specs.append(SLOSpec(
        name="dispatch_overhead", type="gauge",
        metric="pint_tpu_dispatch_overhead_frac",
        objective=0.1, budget=0.5))
    # ISSUE 14: numerical-health incident rate against the dispatch
    # volume — a sustained numerics episode (NaN storms, CG budget
    # exhaustion, drift beyond band) burns this budget and fires the
    # slo_burn flight dump on top of the per-incident numerics:<...>
    # dumps, the same escalation shape as shed_rate
    specs.append(SLOSpec(
        name="numerics_incident_rate", type="ratio",
        bad=["pint_tpu_health_incidents_total"],
        total=["pint_tpu_dispatch_dispatches_total"],
        budget=0.01))
    return specs


class SLOWatchdog:
    """Module docstring. ``tick()`` is the public sampling step —
    the daemon thread calls it on the interval; tests call it
    directly with an injected ``now`` for determinism."""

    def __init__(self, specs: Optional[List[SLOSpec]] = None,
                 interval_s: Optional[float] = None,
                 registry=None, clock=time.monotonic):
        from pint_tpu import config

        self.specs = list(specs if specs is not None
                          else config.slo_specs())
        self.interval_s = float(config.slo_interval_s()
                                if interval_s is None else interval_s)
        self.registry = registry or om.get_registry()
        self.clock = clock
        slow = max((s.slow_s for s in self.specs), default=300.0)
        cap = int(min(4096, max(16, slow / max(self.interval_s, 1e-3)
                                + 4)))
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._burning: set = set()
        self._lock = locks.make_lock("obs.slo")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fires = 0
        self.ticks = 0
        self.last_fired: Optional[str] = None

    # -- sampling ------------------------------------------------------

    def _observe(self, spec: SLOSpec) -> dict:
        reg = self.registry
        if spec.type == "latency":
            m = reg.get(spec.metric)
            counts: Dict[int, int] = {}
            total = 0
            if m is not None and hasattr(m, "matching"):
                for h in m.matching(spec.labels):
                    with h._lock:
                        total += h.count
                        for k, v in h.counts.items():
                            counts[k] = counts.get(k, 0) + v
            return {"counts": counts, "count": total}
        if spec.type == "ratio":
            return {"bad": sum(reg.total(n) for n in spec.bad),
                    "total": sum(reg.total(n) for n in spec.total)}
        m = reg.get(spec.metric)
        vals = [v for _, v in m.series()] if m is not None else []
        return {"value": max(vals) if vals else None}

    def tick(self, now: Optional[float] = None) -> List[str]:
        """Take one sample, evaluate every spec, fire burns.
        Returns the names that fired THIS tick."""
        now = self.clock() if now is None else now
        sample = {"_t": now}
        for spec in self.specs:
            sample[spec.name] = self._observe(spec)
        om.sample_device_memory()
        fired: List[str] = []
        with self._lock:
            self._ring.append(sample)
            self.ticks += 1
            for spec in self.specs:
                fb = self._burn(spec, spec.fast_s, sample, now)
                sb = self._burn(spec, spec.slow_s, sample, now)
                if fb is None or sb is None:
                    continue
                if fb >= spec.burn and sb >= spec.burn:
                    if spec.name not in self._burning:
                        self._burning.add(spec.name)
                        self.fires += 1
                        self.last_fired = spec.name
                        fired.append(spec.name)
                elif fb < spec.burn:
                    # the episode ends when the FAST window recovers
                    self._burning.discard(spec.name)
        for name in fired:
            spec = next(s for s in self.specs if s.name == name)
            from pint_tpu import obs

            obs.event("slo.burn", slo=name)
            fpath = obs.flight_dump(f"slo_burn:{name}",
                                    slo=self._spec_status(spec, now))
            # ISSUE 15: automatic one-shot profiler window on the
            # burn — capture the dispatches of the regression WHILE
            # it is happening, cross-linked to this episode's flight
            # dump. One per episode: the watchdog only fires once
            # per burn episode (latched above) and the profiler
            # additionally rate-limits per reason. Never raises.
            from pint_tpu.obs import perf as _perf

            _perf.auto_window(f"slo_burn:{name}", slo=name,
                              flight=fpath)
        return fired

    def _window_base(self, window_s: float, now: float):
        """Latest ring sample at/older than the window start — the
        delta baseline. None until the ring actually SPANS the
        window (an uncovered window must not fire: that is exactly
        the one-sample-spike false positive)."""
        base = None
        for s in self._ring:
            if s["_t"] <= now - window_s:
                base = s
            else:
                break
        return base

    def _burn(self, spec: SLOSpec, window_s: float, cur: dict,
              now: float) -> Optional[float]:
        base = self._window_base(window_s, now)
        if base is None:
            return None
        n_in = sum(1 for s in self._ring
                   if now - window_s < s["_t"] <= now)
        if n_in < spec.min_samples:
            return None
        a, b = base.get(spec.name), cur.get(spec.name)
        if a is None or b is None:
            return None
        if spec.type == "latency":
            d_total = b["count"] - a["count"]
            if d_total < spec.min_events:
                return None
            good = 0
            for k in b["counts"]:
                d = b["counts"].get(k, 0) - a["counts"].get(k, 0)
                le_us = (1 << k) if k else 1
                if le_us <= spec.objective_ms * 1e3:
                    good += d
            err = 1.0 - good / d_total
            return err / max(1e-9, 1.0 - spec.target)
        if spec.type == "ratio":
            d_total = b["total"] - a["total"]
            if d_total < spec.min_events:
                return None
            err = max(0.0, (b["bad"] - a["bad"])) / d_total
            return err / max(1e-9, spec.budget)
        # gauge: violation fraction over the window's samples
        vals = [s[spec.name]["value"] for s in self._ring
                if now - window_s < s["_t"] <= now
                and s.get(spec.name, {}).get("value") is not None]
        if not vals:
            return None
        frac = sum(1 for v in vals if v > spec.objective) / len(vals)
        return frac / max(1e-9, spec.budget)

    # -- reporting -----------------------------------------------------

    def _spec_status(self, spec: SLOSpec, now: float) -> dict:
        cur = self._ring[-1] if self._ring else {"_t": now}
        out = {"name": spec.name, "type": spec.type,
               "burn_threshold": spec.burn,
               "fast_s": spec.fast_s, "slow_s": spec.slow_s}
        for label, w in (("fast_burn", spec.fast_s),
                         ("slow_burn", spec.slow_s)):
            b = self._burn(spec, w, cur, cur["_t"])
            out[label] = None if b is None else round(b, 3)
        out["burning"] = spec.name in self._burning
        return out

    def status(self) -> dict:
        """The ``slo`` block serve snapshots / healthz embed."""
        with self._lock:
            now = self._ring[-1]["_t"] if self._ring \
                else self.clock()
            return {
                "armed": True,
                "interval_s": self.interval_s,
                "ticks": self.ticks,
                "fires": self.fires,
                "last_fired": self.last_fired,
                "specs": [self._spec_status(s, now)
                          for s in self.specs],
            }

    # -- the sampling thread -------------------------------------------

    def start(self) -> "SLOWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="pint-slo")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a broken spec must not kill sampling
                pass


# ------------------------------------------------------------------
# process-global instance (armed by env, like the tracer)
# ------------------------------------------------------------------

_WATCHDOG: Optional[SLOWatchdog] = None
_LOCK = locks.make_lock("obs.slo_global")


def get_watchdog() -> Optional[SLOWatchdog]:
    return _WATCHDOG


def maybe_start() -> Optional[SLOWatchdog]:
    """Arm-and-start from the env ($PINT_TPU_SLO); no-op (returns
    None) when unarmed. Idempotent — the serve engine ctor and the
    daemon both call it."""
    global _WATCHDOG
    from pint_tpu import config

    if not config.slo_enabled():
        return None
    with _LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = SLOWatchdog().start()
        return _WATCHDOG


def status() -> Optional[dict]:
    w = _WATCHDOG
    return w.status() if w is not None else None


def reset():
    """Stop + drop the global watchdog (test isolation, with
    obs.reset)."""
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
        _WATCHDOG = None
