"""Serving-layer benchmark (ISSUE 2): sequential-vs-coalesced request
throughput for a mixed-shape workload through pint_tpu.serve.

The naive serving loop dispatches every request alone (one device
call, one RTT each); the coalescing scheduler groups the same
requests by shape class and dispatches each group as ONE padded
vmapped solve, sharded over the device mesh when one exists. On the
8-virtual-device CPU mesh this bench demonstrates the architectural
win without hardware; on the chip the same stage is queued in
tools/tpu_capture.py (the per-dispatch RTT being amortized is then
0.1-0.25 s, not ~0.3 ms, so the on-chip speedup is far larger).

Run:  python bench_serve.py [--nreq 64] [--repeats 3]
Prints one JSON line per mode and a final speedup record (LAST line
is the artifact: throughputs, batch occupancy, padded waste, compile
count vs bucket count).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_workload(nreq: int):
    """nreq mixed-shape requests over 6 pulsars in three TOA classes
    (50..200 -> buckets 64/128/256) plus polyco phase reads.
    Problems are prebuilt once — the serving-state hot path (a
    service holding hot pulsar states re-solves on every poll), so
    the measured loop is dispatch work, not model assembly. The
    actual builder is ``pint_tpu.serve.workload.build_workload``
    (shared with the pint_serve demo daemon — ONE workload builder,
    per the PR-3 review)."""
    from pint_tpu.serve.workload import BENCH_SIZES
    from pint_tpu.serve.workload import build_workload as _build

    return _build(nreq, sizes=BENCH_SIZES, base=1300, prebuild=True,
                  entry_name="BENCH")


def _drive_sequential(engine, reqs):
    futs = []
    for r in reqs:
        futs.append(engine.submit(r))
        engine.flush()  # the naive loop: one dispatch per request
    for f in futs:
        f.result(timeout=0)


def _drive_coalesced(engine, reqs):
    futs = [engine.submit(r) for r in reqs]
    engine.flush()
    for f in futs:
        f.result(timeout=0)


def run(nreq: int = 64, repeats: int = 3) -> dict:
    """Measure sequential dispatch vs coalesced batching (single
    device, and batch-axis-sharded over the mesh when >1 device);
    returns the speedup record (printed by main as the LAST JSON
    line). The headline speedup is the faster coalesced mode — the
    configuration a deployment would pick. On the virtual CPU mesh
    the sharded mode usually LOSES to single-device coalescing
    (device_put sharding + per-shard dispatch overhead against
    threads that already share the host's cores); it exists to prove
    the path and for real multi-chip meshes where the batch compute
    dominates."""
    import jax

    from pint_tpu.serve import ServeEngine

    backend = jax.default_backend()
    devices = jax.devices()
    mesh = None
    if len(devices) > 1:
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices).reshape(len(devices)),
                    ("pulsar",))
    log(f"backend: {backend}, {len(devices)} device(s), "
        f"mesh={'yes' if mesh is not None else 'no'}")

    fresh = build_workload(nreq)
    seq_eng = ServeEngine(pipeline_depth=1)
    # coalesced = the classic synchronous drain; pipelined = the
    # ISSUE-7 double-buffered drain (config.serve_pipeline_depth in
    # flight) — reported side by side as pipelined-vs-sync
    engines = {"coalesced": ServeEngine(pipeline_depth=1),
               "coalesced_pipelined": ServeEngine()}
    if mesh is not None:
        engines["coalesced_mesh"] = ServeEngine(mesh=mesh)

    # warm every path: compiles happen here, not in the timed loop
    # (the artifact still reports them — the executable bound is the
    # subsystem's point)
    t0 = time.perf_counter()
    _drive_sequential(seq_eng, fresh())
    log(f"sequential warmup (compiles): "
        f"{time.perf_counter() - t0:.2f}s")
    for name, eng in engines.items():
        t0 = time.perf_counter()
        _drive_coalesced(eng, fresh())
        log(f"{name} warmup (compiles): "
            f"{time.perf_counter() - t0:.2f}s")

    seq_s = []
    co_s = {name: [] for name in engines}
    for _ in range(repeats):
        t0 = time.perf_counter()
        _drive_sequential(seq_eng, fresh())
        seq_s.append(time.perf_counter() - t0)
        for name, eng in engines.items():
            t0 = time.perf_counter()
            _drive_coalesced(eng, fresh())
            co_s[name].append(time.perf_counter() - t0)
    seq_best = min(seq_s)
    co_best = {name: min(ts) for name, ts in co_s.items()}
    best_mode = min(co_best, key=co_best.get)
    co_eng = engines[best_mode]

    seq_snap = seq_eng.metrics.snapshot()
    co_snap = co_eng.metrics.snapshot()
    pipe_snap = engines["coalesced_pipelined"].metrics.snapshot()
    print(json.dumps({"metric": "serve_sequential_throughput",
                      "backend": backend, "unit": "req/s",
                      "value": round(nreq / seq_best, 1),
                      "nreq": nreq,
                      "wall_ms": round(seq_best * 1e3, 2),
                      "dispatches": sum(
                          b.batches
                          for b in seq_eng.metrics.buckets.values()),
                      "compile_count": seq_snap["compile_count"]}),
          flush=True)
    rec = {
        "metric": "serve_coalesced_vs_sequential_64req",
        "backend": backend, "unit": "x",
        "value": round(seq_best / co_best[best_mode], 2),
        "nreq": nreq,
        "ndevices": len(devices),
        "coalesced_mode": best_mode,
        "sequential_req_per_s": round(nreq / seq_best, 1),
        "coalesced_req_per_s":
            round(nreq / co_best[best_mode], 1),
        "coalesced_wall_ms":
            round(co_best[best_mode] * 1e3, 2),
        "batch_occupancy": co_snap["batch_occupancy"],
        "padded_waste": co_snap["padded_waste"],
        "compile_count": co_snap["compile_count"],
        "bucket_count": co_snap["bucket_count"],
        "p50_ms": co_snap["p50_ms"],
        "p99_ms": co_snap["p99_ms"],
        # dispatch-supervisor counters (retries, timeouts, breaker
        # state, failovers): a degraded run is labeled in the
        # artifact itself, never silently slow
        "dispatch_supervisor": co_snap.get("dispatch"),
        # dispatch-overhead observability (ISSUE 7): how the number
        # was produced — pipelining configured/achieved + donation
        # (read off the PIPELINED engine, whatever mode won)
        "dispatch_overhead": {
            "pipeline_depth": pipe_snap.get("pipeline_depth"),
            "max_inflight": (pipe_snap.get("dispatch") or {}).get(
                "max_inflight"),
            "donation": pipe_snap.get("donation"),
            "pipelined_vs_sync": round(
                co_best["coalesced"] / co_best["coalesced_pipelined"],
                2),
        },
        "pipelined_wall_ms": round(
            co_best["coalesced_pipelined"] * 1e3, 2),
        # analyzer state (graftlint clean bool + suppression
        # surface): a record from a tree that no longer lints clean
        # carries its own warning label, same policy as dispatch
        "lint": _lint_block(),
        # ISSUE 8 observability: shed counts (admission), per-pool
        # dispatch shares (router), and warm-vs-cold first-request
        # latency through the AOT store (restart)
        "admission": co_snap.get("admission"),
        "router": co_snap.get("router"),
        "restart": measure_restart(),
        # ISSUE 10 observability: log-bucketed latency histograms
        # per (pool, kind, class) x (queue_wait/dispatch_wall/e2e)
        # + tracer/flight-recorder state — the tail view the
        # reservoir p50/p99 above cannot give
        "latency": co_snap.get("latency"),
        "obs": co_snap.get("obs"),
    }
    if "coalesced_mesh" in co_best:
        rec["mesh_sharded_wall_ms"] = round(
            co_best["coalesced_mesh"] * 1e3, 2)
        rec["mesh_sharded_speedup"] = round(
            seq_best / co_best["coalesced_mesh"], 2)
    # ISSUE 15: the ledger-derived attribution blocks — `compiles`
    # summarizes every executable this process built (serve classes
    # carry XLA cost via the ExecutableCache ledger callback), and
    # `roofline` joins those costs against the winning engine's
    # measured per-key dispatch walls
    try:
        from pint_tpu.obs import perf as operf

        rec["compiles"] = operf.ledger_summary()
        roof = operf.roofline_from_latency(
            (co_snap.get("dispatch") or {}).get("latency"), backend)
        if roof is not None:
            rec["roofline"] = roof
    except Exception as e:
        log(f"perf attribution blocks failed: {e!r}")
    # perf-regression verdict against BENCH_BASELINE.json (ISSUE 11)
    try:
        import bench as _bench

        _bench.attach_regress(rec)
    except Exception:
        pass
    log(co_eng.metrics.report())
    return rec


def measure_restart(nreq: int = 8) -> dict:
    """Warm-vs-cold first-request latency through the AOT store
    (ISSUE 8): a cold engine pays trace+compile (+ the one-time AOT
    export) on its first batch; a warm engine restores+primes the
    exported executables at construction and its first batch
    compiles NOTHING (``warm_new_compiles`` is the engine's live jit
    cache count — the Sanitizer-asserted zero of the restart
    oracle)."""
    import tempfile

    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.workload import build_workload as _build

    d = tempfile.mkdtemp(prefix="pint_tpu_aot_")
    fresh = _build(nreq, sizes=(60, 120), base=1500, prebuild=True,
                   entry_name="RESTART")

    def first_batch(eng):
        reqs = fresh()
        t0 = time.perf_counter()
        futs = [eng.submit(r) for r in reqs]
        eng.flush()
        for f in futs:
            f.result(timeout=0)
        return (time.perf_counter() - t0) * 1e3

    cold = ServeEngine(aot_dir=d)
    cold_ms = first_batch(cold)
    cold.stop()
    t0 = time.perf_counter()
    warm = ServeEngine(aot_dir=d)
    restore_ms = (time.perf_counter() - t0) * 1e3
    warm_ms = first_batch(warm)
    jit_n = warm.cache.jit_cache_size()
    restored = warm.cache.aot.restored if warm.cache.aot else 0
    warm.stop()
    return {
        "cold_first_batch_ms": round(cold_ms, 2),
        "warm_restore_ms": round(restore_ms, 2),
        "warm_first_batch_ms": round(warm_ms, 2),
        "warm_vs_cold": round(cold_ms / warm_ms, 2) if warm_ms else None,
        "restored_classes": restored,
        "warm_new_compiles": jit_n,
    }


def run_degraded(nreq: int = 64) -> dict:
    """Coalesced-vs-shed throughput under INJECTED overload (the
    ``serve_degraded`` capture stage): a fault-plan ``overload`` rule
    makes a slice of admissions see exhausted capacity, exercising
    the shed policy mid-burst; the record reports served-vs-shed
    counts, the served throughput, and the labeled admission/router/
    dispatch blocks — degraded serving measured honestly, not
    laundered into a clean number."""
    from pint_tpu.runtime import Fault, FaultPlan
    from pint_tpu.serve import ServeEngine, ServeOverload

    import jax

    fresh = build_workload(nreq)
    eng = ServeEngine()
    # warm compiles outside the measured burst: one clean pass, then
    # one faulted pass — the shed pattern changes the surviving batch
    # sizes, and those shapes' compiles must not pollute the number
    warm = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in warm:
        f.result(timeout=0)
    # two faulted passes; the second (shape-warm) one is measured
    for _ in range(2):
        # the middle half of the burst sees injected overload
        plan = FaultPlan([Fault(match="serve.admit/capacity",
                                kind="overload", after=nreq // 4,
                                count=nreq // 2)])
        rejected = 0
        t0 = time.perf_counter()
        with plan.active():
            futs = []
            for r in fresh():
                try:
                    futs.append(eng.submit(r))
                except ServeOverload:
                    rejected += 1
            eng.flush()
        served = failed = 0
        for f in futs:
            try:
                f.result(timeout=0)
                served += 1
            except Exception:
                failed += 1
        wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    return {
        "metric": "serve_degraded_overload",
        "backend": jax.default_backend(),
        "nreq": nreq,
        "served": served,
        "shed": rejected + failed,
        "unaccounted": nreq - served - rejected - failed,  # must be 0
        "served_req_per_s": round(served / wall, 1) if wall else None,
        "wall_ms": round(wall * 1e3, 2),
        "admission": snap.get("admission"),
        "router": snap.get("router"),
        "dispatch_supervisor": snap.get("dispatch"),
    }


def run_fleet(nreq: int = 48) -> dict:
    """3-worker kill-one throughput curve (ISSUE 19, the
    ``fleet_degraded`` capture stage): a ``FleetFront`` over three
    sync-mode workers serves a fit burst at full strength
    (baseline), then the same burst with one worker KILLED mid-burst
    — its journaled in-flight requests re-home onto the survivors
    and the degraded wall INCLUDES the sweep + re-home replay — then
    a clean survivors-only pass (recovered). The guarantee under
    test: lose a worker, lose ~1/N capacity and ZERO requests
    (``lost`` must be 0; every re-homed future resolves from a
    survivor). Shape warm-up covers BOTH batch paddings (16 at full
    strength, 32 on the survivors) so the degraded number measures
    re-home + serving, not compiles."""
    import os
    import shutil
    import tempfile

    import jax

    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.serve import FitStepRequest, FleetFront
    from pint_tpu.serve.workload import synth_pulsar

    nwork = 3
    pulsars = {k: synth_pulsar(k, 40, base=5100) for k in (0, 1, 2)}
    stock = {k: build_problem(t, m) for k, (m, t) in pulsars.items()}

    def factory(payload):
        return FitStepRequest(problem=stock[payload["k"]],
                              payload=payload)

    def burst(n):
        return [factory({"k": i % len(stock)}) for i in range(n)]

    tmp = tempfile.mkdtemp(prefix="pint_tpu_fleet_bench_")
    front = FleetFront(factory, n=nwork,
                       journal=os.path.join(tmp, "fleet.jsonl"),
                       heartbeat_s=3600.0, lease_ttl_s=7200.0,
                       start=False)

    def flush_live():
        for wid in front.live_workers():
            front.workers[wid].engine.flush()

    rehomed_mid = 0

    def drive(reqs, kill_at=None):
        nonlocal rehomed_mid
        lost = 0
        t0 = time.perf_counter()
        futs = []
        for i, r in enumerate(reqs):
            if kill_at is not None and i == kill_at:
                front.kill_worker("w1")
                rehomed_mid = front.sweep()
            futs.append(front.submit(r))
        flush_live()
        for f in futs:
            try:
                f.result(timeout=0)
            except Exception:
                lost += 1
        return time.perf_counter() - t0, lost

    try:
        # warm-up: full-strength shapes (Pb=16 per worker at
        # nreq=48) AND the survivor shapes (Pb=32: 64 reqs over 3
        # workers pads each worker's bucket to 32 — the same padding
        # the two survivors see post-kill)
        drive(burst(nreq))
        drive(burst(64))
        base_wall = min(drive(burst(nreq))[0] for _ in range(2))
        deg_wall, lost = drive(burst(nreq), kill_at=nreq // 2)
        rec_wall = min(drive(burst(nreq))[0] for _ in range(2))
        # read every post-mortem surface BEFORE stop() tears the
        # engines down and the tempdir (journal included) goes away
        snap = front.metrics.snapshot()
        live = front.live_workers()
        pools = front.health_blocks()
        unacked = len(
            front.workers["w0"].engine.journal.unacknowledged())
    finally:
        try:
            front.stop()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    fleet = snap.get("fleet") or {}
    base_rps = nreq / base_wall if base_wall else None
    deg_rps = nreq / deg_wall if deg_wall else None
    rec_rps = nreq / rec_wall if rec_wall else None
    rec = {
        "metric": "fleet_degraded",
        "backend": jax.default_backend(),
        "unit": "frac",
        # the headline: degraded-vs-baseline served throughput with
        # a third of the fleet dead MID-burst (ideal ~2/3 minus the
        # sweep + re-home replay tax)
        "value": round(deg_rps / base_rps, 3) if base_rps else None,
        "nreq": nreq,
        "workers": nwork,
        "killed": "w1",
        "live": live,
        "lost": lost,                       # must be 0 — the guarantee
        "rehomed": rehomed_mid,
        "counters": fleet.get("counters"),
        "states": fleet.get("workers"),
        "baseline_req_per_s": round(base_rps, 1),
        "degraded_req_per_s": round(deg_rps, 1),
        "recovered_req_per_s": round(rec_rps, 1),
        "recovered_vs_baseline": round(rec_rps / base_rps, 3),
        "journal_unacked": unacked,
        "dispatch_supervisor": snap.get("dispatch"),
        "pools": pools,
        "latency": snap.get("latency"),
        "lint": _lint_block(),
    }
    try:
        import bench as _bench

        _bench.attach_regress(rec)
    except Exception:
        pass
    return rec


def run_append(ntoa: int = 100_000, nnew: int = 128) -> dict:
    """Incremental-append-vs-cold-refit at the 100k-TOA scale
    (ISSUE 12 acceptance): a cold ``AppendTOAsRequest`` accumulates
    the full dataset into the engine's per-pulsar state; the warm
    append then re-converges ``nnew`` new TOAs in O(new) — measured
    against the cost of a cold refit over the combined set. The
    consistency column re-fits the combined set cold and reports the
    worst parameter difference in sigma (the two differ only through
    the re-derived noise-basis span — convergence-tolerance level)."""
    import warnings

    import jax
    import numpy as np

    from pint_tpu.serve import AppendTOAsRequest, ServeEngine

    par = [
        "PSR J0000+0002", "RAJ 12:00:00.0 1", "DECJ 30:00:00.0 1",
        "PMRA 2.0 1", "PMDEC -3.0 1", "PX 1.2 1",
        "F0 300.123456789 1", "F1 -1.0e-15 1",
        "DM 20.0", "PEPOCH 55000", "POSEPOCH 55000",
        "TZRMJD 55000.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "EFAC -be X 1.1", "EQUAD -be X 0.3",
        "TNREDAMP -13.7", "TNREDGAM 3.5", "TNREDC 15",
    ]
    from bench import _make_model_toas

    rng = np.random.default_rng(12)
    mjds = np.sort(rng.uniform(53000.0, 56990.0, ntoa))
    freqs = np.tile([1400.0, 820.0], ntoa // 2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model, toas0 = _make_model_toas(
            par, mjds, freqs, seed=12,
            flag_sets={"be": lambda i: "X"})
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        def new_batch(lo, hi):
            m2 = np.sort(rng.uniform(lo, hi, nnew))
            t = make_fake_toas_fromMJDs(
                m2, model, error_us=1.0,
                freq_mhz=np.tile([1400.0, 820.0], nnew // 2),
                add_noise=True, rng=rng)
            for f in t.flags:
                f["be"] = "X"
            return t

        batch1 = new_batch(56991.0, 56995.0)
        batch2 = new_batch(56995.1, 57000.0)
        from pint_tpu.toa import merge_TOAs

        comb = merge_TOAs([toas0, batch1, batch2])

    eng = ServeEngine()
    t0 = time.perf_counter()
    r_cold = eng.submit(AppendTOAsRequest(
        "bench", toas=toas0, model=model,
        cold=True)).result(timeout=600)
    cold_ms = (time.perf_counter() - t0) * 1e3
    # batch 1 warms the small append class's compile (the serving
    # steady state: compiles are bounded by shape classes and paid
    # once per process, never per request — the same warm-then-
    # measure protocol as the coalescing benchmark)
    eng.submit(AppendTOAsRequest(
        "bench", toas=batch1, model=model)).result(timeout=600)
    t0 = time.perf_counter()
    r_warm = eng.submit(AppendTOAsRequest(
        "bench", toas=batch2, model=model)).result(timeout=600)
    warm_ms = (time.perf_counter() - t0) * 1e3
    # cold REFIT over the combined set (fresh key; shape-warm: the
    # first cold build already compiled this fallback class)
    t0 = time.perf_counter()
    r_refit = eng.submit(AppendTOAsRequest(
        "bench-refit", toas=comb, model=model,
        cold=True)).result(timeout=600)
    refit_ms = (time.perf_counter() - t0) * 1e3
    sig = np.sqrt(np.abs(np.diag(r_refit.cov)))
    worst = float(np.max(np.abs(r_warm.dparams - r_refit.dparams)
                         / sig))
    snap = eng.metrics.snapshot()
    rec = {
        "metric": "serve_append_incremental_vs_cold_100k",
        "backend": jax.default_backend(),
        "ntoa": ntoa, "nnew": nnew,
        "value": round(refit_ms / warm_ms, 2), "unit": "x",
        "cold_build_ms": round(cold_ms, 1),
        "incremental_ms": round(warm_ms, 1),
        "cold_refit_ms": round(refit_ms, 1),
        "consistency_max_sigma": round(worst, 6),
        "ntoa_total_expected": ntoa + 2 * nnew,
        "ntoa_total": r_warm.ntoa_total,
        "cg_iters": r_warm.cg_iters,
        "append": snap.get("append"),
        "dispatch_supervisor": snap.get("dispatch"),
    }
    log(f"append: cold {cold_ms:.0f} ms, incremental "
        f"{warm_ms:.0f} ms, cold refit {refit_ms:.0f} ms -> "
        f"{rec['value']}x, consistency {worst:.2e} sigma")
    return rec


def _lint_block():
    try:
        from pint_tpu.analysis import lint_state_safe

        return lint_state_safe()
    except Exception as e:  # analyzer package unimportable
        return {"clean": None, "error": repr(e)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nreq", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--degraded", action="store_true",
                    help="measure coalesced-vs-shed throughput "
                         "under injected overload instead of the "
                         "speedup artifact")
    ap.add_argument("--fleet", action="store_true",
                    help="measure the 3-worker kill-one fleet "
                         "throughput curve (baseline / degraded "
                         "mid-kill with re-home / recovered) "
                         "instead of the speedup artifact")
    ap.add_argument("--append", action="store_true",
                    help="measure incremental AppendTOAsRequest "
                         "re-convergence vs a cold refit at the "
                         "100k-TOA scale (ISSUE 12)")
    ap.add_argument("--append-ntoa", type=int, default=100_000)
    ap.add_argument("--append-new", type=int, default=128)
    args = ap.parse_args()

    import os

    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        from bench import accelerator_responsive, cpu_fallback_env

        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    import jax

    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        # CPU run: pin the platform (the sitecustomize-registered TPU
        # plugin otherwise wins) and force the 8-virtual-device mesh
        # (same as tests/conftest.py) — both only effective BEFORE
        # the backend initializes, so decide from env, not
        # jax.default_backend()
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    if args.degraded:
        rec = run_degraded(nreq=args.nreq)
    elif args.fleet:
        rec = run_fleet()
    elif args.append:
        rec = run_append(ntoa=args.append_ntoa,
                         nnew=args.append_new)
    else:
        rec = run(nreq=args.nreq, repeats=args.repeats)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
